"""Pytree serialization for the cross-silo file/wire planes.

The reference moves model state between processes as pickled PySyft tensors
over websockets (SURVEY.md §1 "Communication").  The rebuild uses two
self-describing formats with one decoder:

- FILES (``colearn init/train --role client/aggregate``): plain ``.npz`` —
  each leaf under its ``/``-joined tree path plus ``__meta__`` JSON.
  mmap-friendly, loadable by anything that reads npz.
- WIRE (comm/transport.py): ``CLW1`` flat frames — JSON leaf directory +
  concatenated raw buffers + crc32.  No zip container overhead, single
  contiguous payload, integrity-checked.

``bytes_to_pytree`` auto-detects the format, so a silo can hand a wire
payload to the file flow (or vice versa) without caring which produced it.
"""

from __future__ import annotations

import io
import json
import os
import struct
import tempfile
import zlib
from typing import Any

import numpy as np

_META = "__meta__"
_WIRE_MAGIC = b"CLW1"
_WIRE_HLEN = struct.Struct(">I")
_WIRE_PAY = struct.Struct(">QI")      # payload length, crc32


def _dtype_entry(dtype: np.dtype) -> dict:
    """Leaf-directory dtype slots.  ``dtype.str`` is authoritative for
    every builtin dtype, but ml_dtypes extension types (bfloat16,
    float8_*) all stringify as raw void bytes (``'<V2'``) — decoding that
    silently reinterprets the payload.  Those get an explicit dtype-NAME
    slot (``"n"``) the decoder resolves by name instead."""
    entry = {"d": dtype.str}
    if np.dtype(dtype.str) != dtype:
        entry["n"] = dtype.name
    return entry


def _resolve_dtype(entry: dict) -> np.dtype:
    name = entry.get("n")
    if name is None:
        return np.dtype(entry["d"])
    try:
        return np.dtype(name)
    except TypeError:
        # Extension dtypes register with numpy on import; a decoder
        # process that never touched jax/ml_dtypes needs the import first.
        import ml_dtypes  # noqa: F401

        return np.dtype(name)


def _flatten(tree: Any, prefix: str = "") -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            if "/" in str(k):
                raise ValueError(f"key {k!r} contains the path separator '/'")
            out.update(_flatten(tree[k], f"{prefix}{k}/"))
        return out
    if isinstance(tree, (list, tuple)):
        # np.asarray would silently STACK a list of leaves into one array and
        # the round trip would change tree structure; refuse loudly instead.
        # (The wire format is dict-of-arrays; index lists/tuples by position.)
        raise TypeError(
            f"cannot serialize {type(tree).__name__} node at {prefix or '/'!r}: "
            "convert to a dict with string keys first"
        )
    out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> Any:
    tree: dict = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


_NPZ_DTYPES = "__dtypes__"


def save_pytree_npz(path_or_file, tree: Any, meta: dict | None = None) -> None:
    flat = _flatten(tree)
    # npz stores extension dtypes (bfloat16, ...) as raw void bytes with no
    # way back; ship those leaves as flat byte views plus a (name, shape)
    # map the loader re-views through (same pitfall as the CLW1 "n" slot).
    views = {}
    names = {}
    for p, a in flat.items():
        if np.dtype(a.dtype.str) != a.dtype:
            names[p] = [a.dtype.name, list(a.shape)]
            views[p] = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
        else:
            views[p] = a
    views[_META] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8
    ).copy()
    if names:
        views[_NPZ_DTYPES] = np.frombuffer(
            json.dumps(names).encode(), dtype=np.uint8
        ).copy()
    np.savez(path_or_file, **views)


def atomic_save_pytree_npz(path: str, tree: Any,
                           meta: dict | None = None) -> None:
    """Crash-safe :func:`save_pytree_npz`: write to a same-directory temp
    file, fsync, then ``os.replace`` — a reader never observes a torn
    npz, only the old file or the new one.  The temp file is opened as a
    file OBJECT because ``np.savez`` silently appends ``.npz`` to bare
    paths, which would break the replace."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tmp-", suffix=".npz")
    try:
        with os.fdopen(fd, "wb") as f:
            save_pytree_npz(f, tree, meta)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_pytree_npz(path_or_file) -> tuple[Any, dict]:
    z = np.load(path_or_file)
    meta = json.loads(bytes(z[_META]).decode()) if _META in z.files else {}
    names = (json.loads(bytes(z[_NPZ_DTYPES]).decode())
             if _NPZ_DTYPES in z.files else {})
    flat = {}
    for k in z.files:
        if k in (_META, _NPZ_DTYPES):
            continue
        arr = z[k]
        if k in names:
            name, shape = names[k]
            arr = arr.view(_resolve_dtype({"n": name})).reshape(shape)
        flat[k] = arr
    return _unflatten(flat), meta


def pytree_to_bytes(tree: Any, meta: dict | None = None) -> bytearray:
    """Encode as a ``CLW1`` wire frame (the transport's format)."""
    flat = {p: np.ascontiguousarray(a) for p, a in _flatten(tree).items()}
    entries = [{"p": p, "s": list(a.shape), **_dtype_entry(a.dtype)}
               for p, a in flat.items()]
    header = json.dumps({"leaves": entries, "meta": meta or {}},
                        separators=(",", ":")).encode()
    plen = sum(a.nbytes for a in flat.values())
    # Single allocation, single copy: frame assembled in place, each leaf
    # copied straight into its payload slot.
    out = bytearray(len(_WIRE_MAGIC) + _WIRE_HLEN.size + len(header)
                    + _WIRE_PAY.size + plen)
    off = 0
    out[off:off + len(_WIRE_MAGIC)] = _WIRE_MAGIC
    off += len(_WIRE_MAGIC)
    _WIRE_HLEN.pack_into(out, off, len(header))
    off += _WIRE_HLEN.size
    out[off:off + len(header)] = header
    off += len(header)
    pay_hdr_off = off
    off += _WIRE_PAY.size
    pay_start = off
    for a in flat.values():
        n = a.nbytes
        if n:
            np.frombuffer(out, dtype=a.dtype, count=a.size, offset=off)[
                :
            ] = a.reshape(-1)
        off += n
    crc = zlib.crc32(memoryview(out)[pay_start:])
    _WIRE_PAY.pack_into(out, pay_hdr_off, plen, crc)
    return out                        # bytes-like; avoids a full-frame copy


def wire_frame_length(tree: Any, meta: dict | None = None) -> int:
    """Exact length of the ``CLW1`` frame :func:`pytree_to_bytes` would
    produce, WITHOUT building it — header JSON only, no payload copy.
    Lets the downlink compressor report true bytes-saved (frame vs frame,
    not raw-leaf-bytes vs frame) at negligible cost."""
    flat = _flatten(tree)
    entries = [{"p": p, "s": list(a.shape) or [1], **_dtype_entry(a.dtype)}
               for p, a in flat.items()]   # `or [1]`: 0-d leaves encode (1,)
    header = json.dumps({"leaves": entries, "meta": meta or {}},
                        separators=(",", ":")).encode()
    return (len(_WIRE_MAGIC) + _WIRE_HLEN.size + len(header)
            + _WIRE_PAY.size + sum(a.nbytes for a in flat.values()))


def _wire_to_pytree(data: bytes) -> tuple[Any, dict]:
    off = len(_WIRE_MAGIC)
    (hlen,) = _WIRE_HLEN.unpack_from(data, off)
    off += _WIRE_HLEN.size
    header = json.loads(data[off:off + hlen].decode())
    off += hlen
    plen, crc = _WIRE_PAY.unpack_from(data, off)
    off += _WIRE_PAY.size
    payload = memoryview(data)[off:off + plen]
    if zlib.crc32(payload) != crc:
        raise ValueError("wire payload failed crc32 integrity check")
    flat: dict[str, np.ndarray] = {}
    pos = 0
    for e in header["leaves"]:
        dtype = _resolve_dtype(e)
        shape = tuple(e["s"])
        n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        # copy() detaches each leaf from the big frame buffer (and makes it
        # writable); leaves are consumed as independent arrays downstream.
        flat[e["p"]] = np.frombuffer(
            payload[pos:pos + n], dtype=dtype
        ).reshape(shape).copy()
        pos += n
    if pos != plen:
        raise ValueError(f"wire payload size mismatch: {pos} != {plen}")
    return _unflatten(flat), header.get("meta", {})


def bytes_to_pytree(data: bytes) -> tuple[Any, dict]:
    """Decode either format (CLW1 wire frame or npz), auto-detected."""
    if data[: len(_WIRE_MAGIC)] == _WIRE_MAGIC:
        return _wire_to_pytree(data)
    return load_pytree_npz(io.BytesIO(data))
