"""Host-keyed persistent XLA compile cache.

One shared implementation of the scheme that previously lived as three
diverging copies (tests/conftest.py, __graft_entry__.py,
scripts/run_baseline_configs.py): persist compiled executables under a
directory keyed by the host's CPU feature set — XLA:CPU AOT results
loaded on a host with different features can SIGILL — so the first run
pays the compile (a full-size BERT round program costs ~15 min on one
CPU core) and every later run on the same host loads it in seconds.

Best-effort by design: cache setup must never break the caller, so every
failure path degrades to "no persistent cache".
"""

from __future__ import annotations

import hashlib
import os


def host_key() -> str:
    """Stable 10-hex digest of this host's CPU feature lines."""
    try:
        with open("/proc/cpuinfo") as f:
            # x86 lists "flags", aarch64 lists "Features".
            feats = sorted(
                {line for line in f if line.startswith(("flags", "Features"))}
            )
    except OSError:
        feats = []
    if not feats:
        import platform

        feats = [platform.machine(), platform.processor()]
    return hashlib.sha1("".join(feats).encode()).hexdigest()[:10]


def enable_host_keyed_cache(root: str, dirname: str = ".jax_cache",
                            export_env: bool = False) -> str | None:
    """Point jax's persistent compilation cache at <root>/<dirname>/<hostkey>.

    ``export_env=True`` additionally exports JAX_COMPILATION_CACHE_DIR /
    JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS so spawned subprocesses
    (multi-process tests, CLI federation children) share the cache.
    Returns the cache path, or None if setup failed.
    """
    try:
        import jax

        cache = os.path.join(root, dirname, host_key())
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        if export_env:
            os.environ["JAX_COMPILATION_CACHE_DIR"] = cache
            os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "1.0"
        return cache
    except Exception:
        return None
