"""Pytree arithmetic used throughout the framework.

The reference aggregator does its weighted averaging with host-side
``torch.Tensor`` copies inside a Python loop (SURVEY.md §3a "host-side
fed_avg weighted mean").  Here every model/optimizer state is a plain JAX
pytree and all the averaging math is expressed as jitted tree maps so XLA
can fuse it and, under ``shard_map``, lower the reduction to ``lax.psum``
over ICI (BASELINE.json ``north_star``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * s, tree)


def tree_dot(a: Pytree, b: Pytree) -> jax.Array:
    """Sum of elementwise products over every leaf (a flat inner product)."""
    leaves = jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b)
    return jax.tree.reduce(jnp.add, leaves, jnp.zeros((), jnp.float32))


def tree_sq_norm(tree: Pytree) -> jax.Array:
    """Squared L2 norm across all leaves (float32 accumulation)."""
    leaves = jax.tree.map(lambda x: jnp.sum(jnp.square(x.astype(jnp.float32))), tree)
    return jax.tree.reduce(jnp.add, leaves, jnp.zeros((), jnp.float32))


def tree_global_norm(tree: Pytree) -> jax.Array:
    return jnp.sqrt(tree_sq_norm(tree))


def tree_size(tree: Pytree) -> int:
    """Total number of scalar parameters (static, host-side)."""
    return sum(int(x.size) for x in jax.tree.leaves(tree))


def tree_weighted_mean(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Weighted mean over the leading (client) axis of every leaf.

    ``stacked`` has leaves of shape ``(C, ...)``; ``weights`` has shape
    ``(C,)``.  This is FedAvg's aggregation step (SURVEY.md §2
    "fed_avg(weights, sizes)") expressed as one fused XLA reduction.  A
    zero total weight (e.g. every sampled client was a straggler) safely
    returns zeros instead of NaN so the server update becomes a no-op.
    """
    total = jnp.sum(weights)
    denom = jnp.where(total > 0, total, 1.0)

    def _mean(leaf):
        w = weights.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(jnp.float32)
        return (jnp.sum(leaf.astype(jnp.float32) * w, axis=0) / denom).astype(leaf.dtype)

    return jax.tree.map(_mean, stacked)


def tree_weighted_sum(stacked: Pytree, weights: jax.Array) -> Pytree:
    """Weighted sum over the leading (client) axis (use with a later psum)."""

    def _sum(leaf):
        w = weights.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(jnp.float32)
        return jnp.sum(leaf.astype(jnp.float32) * w, axis=0)

    return jax.tree.map(_sum, stacked)


def tree_cast(tree: Pytree, dtype) -> Pytree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_stack(trees: list) -> Pytree:
    """Stack a Python list of identically-structured pytrees along axis 0."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_index(stacked: Pytree, i) -> Pytree:
    """Select index ``i`` along the leading axis of every leaf."""
    return jax.tree.map(lambda x: x[i], stacked)
