"""Experiment configuration.

The reference parameterizes its scripts with argparse flags (SURVEY.md §2
"Config/scripts": host/port, broker, rounds, epochs, lr, client count).  The
rebuild uses frozen dataclasses so a whole experiment is one hashable value
that can be threaded into jit as static configuration, and ships a registry
mirroring the five driver benchmark configs from BASELINE.json ``configs``.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Optional

# Measured dense/flash crossover (PERF.md §1b): at seq 128 the Pallas flash
# kernel LOSES to dense (1.55 vs 2.12 rounds/sec on the config-#4 BERT) —
# tiling overhead only pays for itself once the O(L^2) score matrix stops
# fitting in VMEM, around L≈1-2k on v5-lite.  Below this length the guard
# warns; dense is both faster and numerically identical.
FLASH_SEQ_CROSSOVER = 1024


def validate_experiment(config: "ExperimentConfig") -> None:
    """Cross-field sanity checks for perf footguns.

    Warns rather than raises: every combination here EXECUTES correctly,
    it is just measured-slower than the obvious alternative, and a user
    sweeping configs must be able to override a heuristic.  Called by
    ``FederatedLearner.__init__`` so every entry path (CLI, from_config,
    direct construction) passes through it once."""
    m = config.model
    if m.attn_impl == "flash" and m.seq_len < FLASH_SEQ_CROSSOVER:
        warnings.warn(
            f"attn_impl='flash' at seq_len={m.seq_len}: dense attention is "
            f"measured FASTER below seq_len~{FLASH_SEQ_CROSSOVER} (PERF.md "
            "§1b: 2.12 vs 1.55 rounds/sec at L=128 on the config-#4 BERT); "
            "use attn_impl='dense' unless you are measuring the kernel "
            "itself",
            # Attribute to validate_experiment's caller (engine __init__):
            # the call depth from user code varies (direct construction vs
            # from_config), so no fixed level reaches the user frame — the
            # message itself carries the identifying config values instead.
            stacklevel=2,
        )


def validate_robustness(config: "ExperimentConfig") -> None:
    """Hard checks on the comm-plane robustness knobs.  These RAISE
    (unlike :func:`validate_experiment`'s perf warnings): a quorum above
    1.0 or an eviction threshold of 0 is not a slow configuration, it is
    a meaningless one.  Called by both socket coordinators and the worker
    entrypoints."""
    run, fed = config.run, config.fed
    if run.evict_after < 1:
        raise ValueError(f"evict_after must be >= 1, got {run.evict_after}")
    if not 0.0 <= fed.min_cohort_fraction <= 1.0:
        raise ValueError(
            "min_cohort_fraction must be in [0, 1], got "
            f"{fed.min_cohort_fraction}"
        )
    if run.comm_retries < 0:
        raise ValueError(
            f"comm_retries must be >= 0, got {run.comm_retries}")
    if run.comm_backoff_base < 0 or run.comm_backoff_max < 0:
        raise ValueError("comm backoff values must be >= 0")
    if fed.lr_spike_round < -1:
        raise ValueError(
            f"lr_spike_round must be >= -1, got {fed.lr_spike_round}")
    if fed.lr_spike_multiplier <= 0:
        raise ValueError(
            "lr_spike_multiplier must be positive, got "
            f"{fed.lr_spike_multiplier}")
    if run.worker_enroll_timeout <= 0:
        raise ValueError(
            "worker_enroll_timeout must be positive, got "
            f"{run.worker_enroll_timeout}"
        )
    from colearn_federated_learning_tpu.fed.compression import SCHEMES

    if fed.compress not in SCHEMES:
        raise ValueError(
            f"unknown compress {fed.compress!r} (use {SCHEMES})"
        )
    if fed.compress_down not in SCHEMES:
        raise ValueError(
            f"unknown compress_down {fed.compress_down!r} (use {SCHEMES})"
        )
    if not 0.0 < fed.topk_fraction <= 1.0:
        raise ValueError(
            f"topk_fraction must be in (0, 1], got {fed.topk_fraction}"
        )
    if fed.secure_agg and fed.compress_feedback:
        raise ValueError(
            "secure_agg cannot carry uplink error feedback: masked updates "
            "are dense by construction (lossy compression would break the "
            "pairwise mask cancellation), so there is no compression "
            "residual to feed back"
        )
    if fed.topk_adaptive:
        if (fed.compress not in ("topk", "topk8")
                or not fed.compress_feedback):
            raise ValueError(
                "topk_adaptive steers density off the error-feedback "
                "residual norm, so it needs compress='topk'/'topk8' AND "
                "compress_feedback=True"
            )
        if not (0.0 < fed.topk_min_fraction
                <= fed.topk_max_fraction <= 1.0):
            raise ValueError(
                "topk_adaptive needs 0 < topk_min_fraction <= "
                "topk_max_fraction <= 1, got "
                f"[{fed.topk_min_fraction}, {fed.topk_max_fraction}]"
            )
    if fed.lora_rank < 0:
        raise ValueError(f"lora_rank must be >= 0, got {fed.lora_rank}")
    if fed.lora_rank > 0:
        if fed.lora_alpha <= 0:
            raise ValueError(
                f"lora_alpha must be positive, got {fed.lora_alpha}")
        if fed.lora_merge_every < 1:
            raise ValueError(
                "lora_merge_every must be >= 1, got "
                f"{fed.lora_merge_every}"
            )
        if fed.compress_down != "none":
            raise ValueError(
                "lora_rank > 0 replaces the broadcast with a base+factor "
                "frame; the downlink delta-cache protocol (compress_down) "
                "does not compose with it — factor uplink compression "
                "(fed.compress) is the supported knob"
            )
        if fed.strategy not in ("fedavg", "fedprox"):
            raise ValueError(
                "lora_rank > 0 folds FACTOR deltas, which the adaptive "
                "server optimizers' params-shaped moment state cannot "
                f"consume — use fedavg/fedprox, not {fed.strategy!r}"
            )
        # NOTE what is deliberately ALLOWED: compress="topk"/"topk8"
        # (+feedback / adaptive density) applies the sparse codec TO THE
        # FACTORS, and secure_agg masks the (dense) factor tree — the
        # secure_agg x compress conflict keeps its existing wire-plane
        # rejection (comm/worker.py __init__), identical under lora.
    if run.num_aggregators < 0:
        raise ValueError(
            f"num_aggregators must be >= 0, got {run.num_aggregators}")
    if run.num_aggregators and run.agg_heartbeat_timeout <= 0:
        raise ValueError(
            "agg_heartbeat_timeout must be positive, got "
            f"{run.agg_heartbeat_timeout}"
        )
    if run.agg_buffer_interval_s <= 0:
        raise ValueError(
            "agg_buffer_interval_s must be positive, got "
            f"{run.agg_buffer_interval_s}"
        )


@dataclasses.dataclass(frozen=True)
class DataConfig:
    dataset: str = "mnist"            # registry name (data/registry.py)
    num_clients: int = 10
    partition: str = "iid"            # "iid" | "dirichlet" | "pathological"
    dirichlet_alpha: float = 0.5      # non-IID skew (BASELINE config #2)
    max_examples_per_client: int = 0  # 0 = derive from dataset size


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str = "mlp"                 # models/registry.py name
    num_classes: int = 10
    # Family-specific knobs (ignored by families that don't use them):
    hidden_dim: int = 200             # MLP
    depth: int = 2                    # MLP layers / transformer blocks
    width: int = 64                   # CNN base channels / embed dim
    num_heads: int = 4                # transformers
    patch_size: int = 16              # ViT
    seq_len: int = 128                # text models
    vocab_size: int = 30522           # BERT wordpiece vocab size
    dtype: str = "float32"            # compute dtype ("bfloat16" on TPU)
    attn_impl: str = "dense"          # dense | flash (pallas) | ring/ulysses (SP)
    num_experts: int = 4              # MoE families (models/moe.py)
    moe_aux_weight: float = 0.01      # Switch load-balance loss weight
    # Rematerialize transformer blocks under autodiff (jax.checkpoint):
    # trades recompute FLOPs for activation HBM — how deep models fit
    # long local training on a chip.
    remat: bool = False
    # CNN MFU levers (PERF.md §1: the north-star CNN sits near 25% MFU
    # with an op-mix explanation — the 3-channel stem conv wastes the
    # MXU's 128-lane contraction dim and GroupNorm is bandwidth-bound):
    # - stem="space_to_depth": fold 2x2 spatial patches into channels
    #   (32x32x3 -> 16x16x12) before the first conv — 4x fewer positions,
    #   4x more contraction channels, same receptive-field economics.
    # - norm="none": drop GroupNorm entirely (measure accuracy cost).
    # Defaults preserve the measured baseline model exactly.
    stem: str = "conv"                # conv | space_to_depth (CNN)
    norm: str = "group"               # group | none (CNN)


@dataclasses.dataclass(frozen=True)
class FedConfig:
    strategy: str = "fedavg"          # fedavg | fedprox | fedadam | fedyogi | scaffold | fednova
    rounds: int = 20
    cohort_size: int = 0              # clients sampled per round; 0 = all
    local_epochs: int = 1
    local_steps: int = 0              # if >0 overrides epochs with a step budget
    batch_size: int = 32
    lr: float = 0.1
    # Client-lr schedule ACROSS ROUNDS (fed/strategies.lr_scale_for_round):
    # the per-step optimizer keeps ``lr`` but every update is scaled by an
    # in-graph factor computed from the round index — warmup ramps over
    # ``warmup_rounds``, cosine decays over the config's ``rounds`` horizon
    # to ``lr_min_fraction``·lr.  Constant lr was the round-3 text-config
    # bottleneck (curves cut off mid-climb).
    lr_schedule: str = "constant"     # constant | cosine | warmup_cosine
    warmup_rounds: int = 0
    lr_min_fraction: float = 0.0      # cosine floor as a fraction of lr
    momentum: float = 0.9
    local_optimizer: str = "sgd"      # sgd | adam | adamw (client-side)
    prox_mu: float = 0.0              # FedProx μ (BASELINE config #3: 0.01)
    server_lr: float = 1.0            # server-side step on the mean delta
    # Byzantine-robust aggregation (fed/robust.py): replaces the weighted
    # mean with an order statistic / distance-based selection over the
    # cohort (see robust.AGGREGATORS for the canonical list).
    aggregator: str = "mean"          # mean | median | trimmed_mean | krum
    # Per-side trim for trimmed_mean; the assumed Byzantine FRACTION f/n
    # for krum (both need floor(trim_fraction * cohort) >= 1).
    trim_fraction: float = 0.1
    # Hierarchical (edge -> cloud) federation (fed/hierarchical.py):
    # >= 2 edge groups run local rounds; cloud syncs every sync_period.
    edge_groups: int = 0              # 0/1 = flat federation
    edge_sync_period: int = 2
    server_beta1: float = 0.9         # FedAdam/FedYogi
    server_beta2: float = 0.99
    server_eps: float = 1e-3
    # Straggler handling (SURVEY.md §5 "failure detection"): each client gets
    # a per-round step budget; clients whose budget falls below
    # ``straggler_min_steps`` are dropped from the weighted average.
    straggler_prob: float = 0.0
    straggler_min_fraction: float = 0.25
    # Privacy hooks (BASELINE.json north_star: on-device DP + secure agg).
    dp_clip: float = 0.0              # 0 disables clipping
    dp_noise_multiplier: float = 0.0  # Gaussian sigma = mult * clip
    dp_delta: float = 1e-5            # δ at which the accountant reports ε
    # Adaptive clipping (quantile tracking; privacy/dp.py): dp_clip becomes
    # the INITIAL clip and follows the dp_target_quantile of update norms.
    dp_adaptive_clip: bool = False
    dp_target_quantile: float = 0.5
    dp_clip_lr: float = 0.2           # η_C of the geometric clip update
    dp_bit_noise: float = 0.0         # σ_b on the bit sum; 0 = cohort/20
    secure_agg: bool = False
    secure_agg_neighbors: int = 0     # 0 = all-pairs masks; k = random ring
    # WIRE-plane pair-key agreement (comm/keyexchange.py): "dh" (default)
    # negotiates per-pair Diffie-Hellman secrets over the broker so the
    # coordinator cannot unmask any single client; "shared_seed" derives
    # pair keys from the experiment seed (coordinator-trusted — only
    # appropriate when the aggregator is trusted or for broker-less
    # tests).  The ENGINE plane ignores this: a simulation holds every
    # client in one process regardless.
    secure_agg_key_exchange: str = "dh"   # dh | shared_seed
    # Dropout-recovery threshold (privacy/dropout.py): each client
    # Shamir-shares its round secrets across its recovery set (its pairing
    # partners) and reconstruction needs ceil(threshold · set_size)
    # surviving shares.  Higher tolerates fewer dropouts but forces a
    # bigger coalition to break a dead client's masks; 0.5 matches the
    # Bonawitz honest-majority setting.
    secure_agg_threshold: float = 0.5
    # UPLINK update compression on the wire/file planes
    # (fed/compression.py): workers compress their delta before it rides
    # the socket; the coordinator's StreamingFolder folds topk frames
    # sparse-natively (O(k) per contribution, comm/aggregation.py).
    compress: str = "none"            # none | int8 | topk
    # UPLINK error feedback (comm/worker.py): carry the compression
    # residual (delta - decompress(compress(delta))) into the next
    # round's delta before compressing — symmetric to the downlink
    # encoder's reconstruction-base feedback.  Only engages when
    # ``compress`` is lossy; reset on resync/param-cache miss; rejected
    # under secure_agg (masked updates are dense by construction).
    compress_feedback: bool = False
    # Topk keep density (fraction of entries kept per leaf) for the
    # UPLINK codec.  Feedback de-biases sparsification, which makes the
    # density a real accuracy/bytes knob rather than a fixed bias cap.
    topk_fraction: float = 0.05
    # Adaptive per-round topk density (comm/worker.py _adapt_topk): each
    # worker steers its effective fraction off the round-over-round trend
    # of its error-feedback residual norm (growing residual → widen,
    # shrinking → tighten), clipped to [topk_min_fraction,
    # topk_max_fraction].  Requires compress="topk" + compress_feedback
    # (the controller's signal IS the feedback residual).
    topk_adaptive: bool = False
    topk_min_fraction: float = 0.01
    topk_max_fraction: float = 0.25
    # DOWNLINK compression (synchronous coordinator broadcast): ship the
    # server delta through the same codecs against a worker-side param
    # cache (comm/downlink.py).  "none" keeps the broadcast byte-identical
    # to builds without the feature.
    compress_down: str = "none"       # none | int8 | topk
    # Aggregation quorum for the socket coordinators: a round whose
    # completed-update count falls below ceil(fraction * cohort) becomes
    # an explicit no-op (the secure-agg discarded-round convention)
    # instead of silently averaging a couple of survivors.  0 disables —
    # today's behavior, and the default.
    min_cohort_fraction: float = 0.0
    # Rank-r LoRA adapter federation (fed/lora.py): clients train and
    # ship ONLY low-rank factors for the partition-rule-targeted matmul
    # params (uplink O(r·d) instead of O(model)); the server folds
    # factor trees and merges B·A·(alpha/r) into the global model every
    # ``lora_merge_every`` aggregations.  0 disables — round records and
    # wire frames stay byte-identical to builds without the feature.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_merge_every: int = 10
    # Chaos knob for the convergence observatory's divergence gate
    # (scripts/learn_smoke.py): multiply the client lr by
    # ``lr_spike_multiplier`` for exactly round ``lr_spike_round``.
    # The gate is config-static (fed/strategies.lr_scale_for_round), so
    # default graphs — and round records — are byte-identical with the
    # knob off.  -1 disables.
    lr_spike_round: int = -1
    lr_spike_multiplier: float = 1.0


@dataclasses.dataclass(frozen=True)
class RunConfig:
    name: str = "default"
    seed: int = 0
    backend: str = "auto"             # "auto" | "tpu" | "cpu"  (CLI --backend)
    mesh_axis: str = "clients"
    seq_axis: str = "seq"             # SP axis (attn_impl="ring"/"ulysses")
    tp_axis: str = "model"            # tensor/expert-parallel axis (parallel/tp.py)
    tp_size: int = 1                  # model-axis size for from_config meshes
    log_every: int = 1
    eval_every: int = 1
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0         # 0 disables
    # Shard-native streaming checkpoints (ckpt/streaming.py): per-shard
    # CRC-checked files + a manifest commit marker fsynced last, restore
    # re-shards onto the current mesh without full-tree assembly.  False
    # keeps the orbax RoundCheckpointer path byte-identical to before.
    ckpt_stream: bool = False
    profile_dir: Optional[str] = None  # jax.profiler trace output (rounds 1-2)
    trace_dir: Optional[str] = None    # span-trace Chrome JSON output dir
    trace_rounds: int = 0              # trace only the first N rounds (0 = all)
    # --- comm-plane robustness (comm/coordinator.py, comm/worker.py) ----
    evict_after: int = 3               # consecutive failed rounds → evicted
    worker_enroll_timeout: float = 3600.0  # worker await_role budget (s)
    comm_retries: int = 2              # transient-failure retries per request
    comm_backoff_base: float = 0.05    # full-jitter backoff base (s)
    comm_backoff_max: float = 2.0      # backoff cap (s)
    # Aggregator tree (comm/aggregator.py): N real aggregator processes
    # each fold one cohort slice and ship one partial sum to the root.
    # 0 = flat federation (every uplink byte lands on the coordinator).
    num_aggregators: int = 0
    # Bounded-deadline failure detection: an aggregator whose retained
    # heartbeat is older than this is treated as dead at dispatch and its
    # slices re-home to live siblings.
    agg_heartbeat_timeout: float = 5.0
    # Tree-async per-slice fold cadence target (seconds): each buffered
    # aggregator auto-sizes its fold threshold K so one partial ships
    # upstream about this often at the slice's observed arrival rate.
    agg_buffer_interval_s: float = 2.0
    # Device-resident fold (--fold-device, ops/fold_kernel.py): server
    # folds run through the fused batched kernel — in-kernel topk8
    # dequant + weighting + scatter, one compile per model — instead of
    # the per-update host-numpy scatter.  The host path stays the
    # bitwise parity oracle; False keeps it byte-identical to before.
    fold_device: bool = False
    # Per-device health ledger (telemetry/health.py): directory the
    # coordinator/aggregator/fleetsim planes write durable straggler
    # attribution into.  None = plane off, no extra I/O, and round
    # records stay byte-identical to the pre-health format.
    health_dir: Optional[str] = None
    # Deterministic fault injection (faults/): path to a FaultPlan JSON
    # installed as the transport interposer; None = no fault layer at all.
    fault_plan: Optional[str] = None
    fault_seed: int = 0
    # Convergence observatory (telemetry/convergence.py): stamp conv_*
    # learning-health keys on round records and export learn.* metrics.
    # Off by default — default round records stay byte-identical (pinned
    # by tests on the sync, async, and fleetsim planes).
    learn_observe: bool = False


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    fed: FedConfig = dataclasses.field(default_factory=FedConfig)
    run: RunConfig = dataclasses.field(default_factory=RunConfig)

    def replace(self, **sections) -> "ExperimentConfig":
        return dataclasses.replace(self, **sections)


def _cfg(**kw) -> ExperimentConfig:
    return ExperimentConfig(**kw)


# The five driver benchmark configs (BASELINE.json "configs", quoted in
# BASELINE.md).  Model scale knobs follow the named architectures; dataset
# shapes come from data/registry.py.
CONFIGS: dict[str, ExperimentConfig] = {
    # 1. "FedAvg 2-layer MLP on MNIST, 10 simulated clients (CPU baseline)"
    "mnist_mlp_fedavg": _cfg(
        data=DataConfig(dataset="mnist", num_clients=10, partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=200, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=20, local_epochs=1,
                      batch_size=32, lr=0.1, momentum=0.9),
        run=RunConfig(name="mnist_mlp_fedavg"),
    ),
    # 2. "FedAvg CNN on CIFAR-10, 100 non-IID clients (Dirichlet α=0.5)"
    "cifar10_cnn_fedavg": _cfg(
        data=DataConfig(dataset="cifar10", num_clients=100,
                        partition="dirichlet", dirichlet_alpha=0.5),
        model=ModelConfig(name="cnn", num_classes=10, width=64,
                          dtype="bfloat16"),
        fed=FedConfig(strategy="fedavg", rounds=100, cohort_size=20,
                      local_epochs=1, batch_size=32, lr=0.05, momentum=0.9),
        run=RunConfig(name="cifar10_cnn_fedavg"),
    ),
    # 3. "FedProx ResNet-18 on CIFAR-100, 100 clients, μ=0.01"
    "cifar100_resnet18_fedprox": _cfg(
        data=DataConfig(dataset="cifar100", num_clients=100,
                        partition="dirichlet", dirichlet_alpha=0.5),
        model=ModelConfig(name="resnet18", num_classes=100,
                          dtype="bfloat16"),
        fed=FedConfig(strategy="fedprox", prox_mu=0.01, rounds=100,
                      cohort_size=20, local_epochs=1, batch_size=32,
                      lr=0.05, momentum=0.9),
        run=RunConfig(name="cifar100_resnet18_fedprox"),
    ),
    # 4. "FedAvg BERT-base on AG-News, 50 text clients"
    "agnews_bert_fedavg": _cfg(
        data=DataConfig(dataset="agnews", num_clients=50, partition="iid"),
        model=ModelConfig(name="bert", num_classes=4, width=768, depth=12,
                          num_heads=12, seq_len=128, dtype="bfloat16"),
        fed=FedConfig(strategy="fedavg", rounds=50, cohort_size=10,
                      local_epochs=1, batch_size=16, lr=5e-5, momentum=0.0,
                      local_optimizer="adam",
                      lr_schedule="warmup_cosine", warmup_rounds=5,
                      lr_min_fraction=0.1),
        run=RunConfig(name="agnews_bert_fedavg"),
    ),
    # 5. "Cross-silo ViT-B/16 on FEMNIST, 3400 clients → v5e-256"
    "femnist_vit_cross_silo": _cfg(
        data=DataConfig(dataset="femnist", num_clients=3400,
                        partition="dirichlet", dirichlet_alpha=0.3),
        model=ModelConfig(name="vit_b16", num_classes=62, width=768,
                          depth=12, num_heads=12, patch_size=16,
                          dtype="bfloat16"),
        fed=FedConfig(strategy="fedavg", rounds=100, cohort_size=256,
                      local_epochs=1, batch_size=16, lr=0.03, momentum=0.9,
                      lr_schedule="warmup_cosine", warmup_rounds=5,
                      lr_min_fraction=0.05),
        run=RunConfig(name="femnist_vit_cross_silo"),
    ),
}


# Thematic parity config beyond the five BASELINE entries: the
# reference's ACTUAL deployment task — IoT network-anomaly detection on
# edge devices (SURVEY.md §0) — as a federated TCN over traffic windows.
CONFIGS["iot_traffic_tcn_fedavg"] = _cfg(
    data=DataConfig(dataset="iot_traffic", num_clients=50,
                    partition="dirichlet", dirichlet_alpha=0.3),
    model=ModelConfig(name="tcn", num_classes=8, width=64, depth=4,
                      dtype="bfloat16"),
    fed=FedConfig(strategy="fedavg", rounds=50, cohort_size=10,
                  local_epochs=1, batch_size=32, lr=0.05, momentum=0.9),
    run=RunConfig(name="iot_traffic_tcn_fedavg"),
)


def get_config(name: str) -> ExperimentConfig:
    if name not in CONFIGS:
        raise KeyError(f"unknown config {name!r}; available: {sorted(CONFIGS)}")
    return CONFIGS[name]
