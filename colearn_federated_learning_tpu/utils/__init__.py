"""Utility layer: pytree math, per-client PRNG derivation, configuration."""
