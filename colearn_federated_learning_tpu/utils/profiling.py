"""Tracing/profiling (SURVEY.md §5: the reference has none; the rebuild
exposes ``jax.profiler`` traces viewable in TensorBoard via
tensorboard-plugin-profile or Perfetto).

``RoundProfiler`` traces a bounded window of federated rounds — by default
rounds 1..2, skipping round 0 so compile time doesn't drown the steady
state — writing to ``RunConfig.profile_dir`` (CLI ``--profile-dir``).
"""

from __future__ import annotations

from typing import Optional

import jax


class RoundProfiler:
    """Start/stop a jax profiler trace around a window of rounds."""

    def __init__(self, profile_dir: Optional[str], first_round: int = 1,
                 num_rounds: int = 2):
        self.profile_dir = profile_dir
        self.first = first_round
        self.last = first_round + num_rounds - 1
        self._active = False

    @property
    def active(self) -> bool:
        """Whether a jax trace window is currently open — callers that
        need a barrier only while tracing (engine.fit) key off this."""
        return self._active

    def before_round(self, round_idx: int) -> None:
        if self.profile_dir and not self._active and round_idx == self.first:
            jax.profiler.start_trace(self.profile_dir)
            self._active = True

    def after_round(self, round_idx: int) -> None:
        if self._active and round_idx >= self.last:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
