"""Version shims for jax APIs the codebase targets.

The code is written against the modern ``jax.shard_map`` surface
(keyword ``mesh``/``in_specs``/``out_specs``, ``axis_names`` selecting
the MANUAL axes, ``check_vma``).  Older jax releases (< 0.6) only ship
``jax.experimental.shard_map.shard_map`` whose equivalent knobs are
``auto`` (the complement of the manual axes) and ``check_rep`` — this
module maps one surface onto the other so the rest of the tree imports
a single name and never version-checks.
"""

from __future__ import annotations

try:                                      # jax >= 0.6: public API
    from jax import shard_map as _shard_map

    HAS_NATIVE_SHARD_MAP = True

    def shard_map(f=None, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=True):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return _shard_map(f, **kw)

except ImportError:                       # jax < 0.6: experimental API
    from jax.experimental.shard_map import shard_map as _shard_map

    HAS_NATIVE_SHARD_MAP = False

    def shard_map(f=None, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=True):
        auto = frozenset()
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma,
                          auto=auto)
