"""Deterministic per-client / per-round PRNG key derivation.

The reference relies on each Python worker process's own torch RNG state
(SURVEY.md §5 "race detection: none; rebuild: deterministic per-client PRNG
keys").  TPU-native simulation runs every client inside one jit program, so
randomness must be functional: each (client, round, purpose) gets a key
derived by ``jax.random.fold_in`` from a single experiment seed.  A given
client's local-training / DP / mask randomness is therefore identical
regardless of which device hosts it.  (Cohort SAMPLING is the one
deliberately placement-dependent draw: the mesh engine samples each
device's slice of the cohort locally — stratified by device — to avoid
cross-device data movement; see fed/engine.py.)
"""

from __future__ import annotations

import jax

# Stable tags so different purposes can never collide even for the same
# (client, round) pair.
_TAG_LOCAL = 0x1
_TAG_SAMPLE = 0x2
_TAG_DP = 0x3
_TAG_MASK = 0x4
_TAG_STRAGGLER = 0x5
_TAG_INIT = 0x6
_TAG_DATA = 0x7
_TAG_MASK_RING = 0x8
_TAG_CLIP_BIT = 0x9


def experiment_key(seed: int) -> jax.Array:
    # uint32 key-data form (not the typed-key form): it flows through
    # shard_map / device_put / checkpoint serialization as a plain array.
    return jax.random.PRNGKey(seed)


def _derive(key: jax.Array, tag: int, *ids) -> jax.Array:
    key = jax.random.fold_in(key, tag)
    for i in ids:
        key = jax.random.fold_in(key, i)
    return key


def init_key(key: jax.Array) -> jax.Array:
    """Model-initialization key."""
    return _derive(key, _TAG_INIT)


def data_key(key: jax.Array) -> jax.Array:
    """Dataset synthesis / partitioning key."""
    return _derive(key, _TAG_DATA)


def client_round_key(key: jax.Array, client_id, round_idx) -> jax.Array:
    """Key for one client's local-training randomness in one round."""
    return _derive(key, _TAG_LOCAL, client_id, round_idx)


def sampling_key(key: jax.Array, round_idx) -> jax.Array:
    """Key for the coordinator's cohort sampling in one round."""
    return _derive(key, _TAG_SAMPLE, round_idx)


def dp_key(key: jax.Array, client_id, round_idx) -> jax.Array:
    """Key for a client's DP noise in one round."""
    return _derive(key, _TAG_DP, client_id, round_idx)


def pair_mask_key(key: jax.Array, client_a, client_b, round_idx) -> jax.Array:
    """Symmetric pairwise key for secure-aggregation masks.

    Ordered so that (a, b) and (b, a) derive the same key — both parties of a
    pair can expand the identical mask stream, which is what makes the masks
    cancel inside the aggregate sum (PAPERS.md, Bonawitz et al. 1611.04482,
    pattern only).
    """
    import jax.numpy as jnp

    lo = jnp.minimum(client_a, client_b)
    hi = jnp.maximum(client_a, client_b)
    return _derive(key, _TAG_MASK, lo, hi, round_idx)


def straggler_key(key: jax.Array, round_idx) -> jax.Array:
    """Key for simulated straggler step budgets in one round."""
    return _derive(key, _TAG_STRAGGLER, round_idx)


def clip_bit_key(key: jax.Array, round_idx) -> jax.Array:
    """Key for the DP noise on the adaptive-clipping bit aggregate
    (privacy/dp.py adaptive quantile tracking) in one round."""
    return _derive(key, _TAG_CLIP_BIT, round_idx)


def mask_ring_key(key: jax.Array) -> jax.Array:
    """Base key for the secure-agg random-ring permutation (the per-round
    ring is derived from this with sampling_key, privacy/secure_agg.py)."""
    return _derive(key, _TAG_MASK_RING)
