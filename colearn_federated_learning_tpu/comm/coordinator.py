"""Federated coordinator over the socket planes.

The reference's coordinator (SURVEY.md §3a) connects to the MQTT broker,
collects ready devices, selects trainers/evaluators, then per round:
serialize global weights → websocket to each trainer → await updates →
host-side ``fed_avg`` → evaluator scoring.  This class is that loop over
the in-tree broker + tensor transport, with three upgrades:

- per-round REQUEST TIMEOUTS: a device that fails or is too slow is
  dropped from this round's weighted average (straggler handling,
  SURVEY.md §5 "failure detection") and the round completes without it;
- the aggregation step and server optimizers are the SAME
  fed/strategies.py code the on-device engine jits (FedAvg/FedProx
  weighting rules included);
- broadcast/collect fans out on a thread per device, so the round time is
  max(device time), not the sum.
"""

from __future__ import annotations

import concurrent.futures as cf
import math
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.comm.broker import BrokerClient
from colearn_federated_learning_tpu.comm.downlink import DownlinkEncoder
from colearn_federated_learning_tpu.comm.enrollment import (
    DeviceInfo,
    EnrollmentManager,
)
from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.comm.transport import (
    RetryPolicy,
    TensorClient,
)
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.utils.config import (
    ExperimentConfig,
    validate_robustness,
)


_pop_worker_spans = protocol.pop_trace_spans


class FederatedCoordinator:
    def __init__(
        self,
        config: ExperimentConfig,
        broker_host: str,
        broker_port: int,
        round_timeout: float = 60.0,
        want_evaluator: bool = True,
        mud_policy=None,
        device_type: Optional[str] = None,
        share_timeout_fraction: float = 0.25,
    ):
        """``mud_policy``: optional :class:`comm.mud.MudPolicy` gating
        enrollment by RFC 8520 device identity (the CoLearn pattern).
        ``device_type``: federate ONLY devices of this MUD type — the
        per-type topology (comm/per_type.py runs one coordinator per
        discovered type over a shared broker)."""
        setup_lib.require_mean_aggregator(config, "the socket coordinator")
        self.config = config
        if config.fed.secure_agg and config.fed.secure_agg_neighbors and (
            config.fed.secure_agg_neighbors % 2
            or config.fed.secure_agg_neighbors < 2
        ):
            # Same eager check as the engine: a bad degree would otherwise
            # error inside every worker's train handler and read as mass
            # dropouts.
            raise ValueError(
                "secure_agg_neighbors must be an even integer >= 2, got "
                f"{config.fed.secure_agg_neighbors}"
            )
        if config.fed.secure_agg and not (
            0.0 < config.fed.secure_agg_threshold <= 1.0
        ):
            raise ValueError(
                "secure_agg_threshold must be in (0, 1], got "
                f"{config.fed.secure_agg_threshold}"
            )
        validate_robustness(config)
        # Aggregator tree (comm/aggregator.py): with run.num_aggregators
        # > 0 the train fan-out goes through N aggregator processes, each
        # folding a contiguous cohort slice; the root folds N partials.
        self.num_aggregators = int(
            getattr(config.run, "num_aggregators", 0) or 0)
        if self.num_aggregators and config.fed.compress_down != "none":
            raise ValueError(
                "the aggregator tree requires compress_down='none': the "
                "per-device resync protocol is not relayed through the "
                "fold tier"
            )
        self._aggs: dict[int, dict] = {}       # agg_id -> host/port/ts
        self._agg_clients: dict[int, TensorClient] = {}
        self._agg_sub: Optional[BrokerClient] = None
        self.agg_heartbeat_timeout = float(
            getattr(config.run, "agg_heartbeat_timeout", 5.0) or 5.0)
        # WAL-backed enrollment ledger (ckpt/wal.EnrollmentLedger): every
        # admission is recorded durably so a resumed coordinator verifies
        # devices against the LEDGER (challenge-on-resume), never against
        # replayable retained broker announcements alone.
        self._ledger = None
        self._ledger_prior: Optional[dict] = None
        self.round_timeout = round_timeout
        # Share-distribution deadline as a fraction of the round budget:
        # a masker too slow to distribute its recovery shares is PRUNED
        # from the cohort here (straggler-aware pruning) instead of
        # becoming an unrecoverable dropout at unmask time.  The train
        # fan-out gets whatever remains of the round budget.
        self.share_timeout_fraction = share_timeout_fraction
        self.want_evaluator = want_evaluator
        # Bounded retry for transient transport failures, budgeted against
        # the shared round deadline (transport.RetryPolicy); comm_retries=0
        # restores single-attempt behavior exactly.
        self.retry = (
            RetryPolicy(max_retries=config.run.comm_retries,
                        backoff_base=config.run.comm_backoff_base,
                        backoff_max=config.run.comm_backoff_max)
            if config.run.comm_retries > 0 else None
        )
        # Aggregation quorum (fed.min_cohort_fraction): sub-quorum rounds
        # are explicit no-ops, not two-survivor averages.  0 disables.
        self.min_cohort_fraction = config.fed.min_cohort_fraction
        # Round spans live here; worker-side spans are adopted from reply
        # metadata so one trace covers the whole federation.  The CLI
        # writes it to RunConfig.trace_dir after fit.
        self.tracer = telemetry.Tracer(process="coordinator")
        self._broker_addr = (broker_host, broker_port)
        self._mud_policy = mud_policy
        self._device_type = device_type
        self._broker = BrokerClient(broker_host, broker_port,
                                    timeout=protocol.CONNECT_TIMEOUT)
        self._enroll = EnrollmentManager(self._broker, mud_policy=mud_policy,
                                         device_type=device_type)
        params = setup_lib.init_global_params(config)
        # LoRA adapter plane (fed/lora.py): with fed.lora_rank > 0 the
        # server keeps a frozen base plus a small factor tree; rounds
        # broadcast a {"base", "factors"} composite, fold FACTOR deltas,
        # and every ``lora_merge_every`` aggregations merge B·A·(α/r)
        # into the (possibly tp-sharded) base shard-wise.  Factors are
        # initialized from the HOST params (shape-only) before sharding.
        self._lora = config.fed.lora_rank > 0
        self._factors = None
        self._lora_agg_count = 0
        self._merge_fn = None
        if self._lora:
            from colearn_federated_learning_tpu.fed import lora as lora_lib

            self._factors = setup_lib.init_lora_factors(config, params)
            _alpha = float(config.fed.lora_alpha)
            _rank = int(config.fed.lora_rank)
            self._merge_fn = jax.jit(
                lambda p, f: lora_lib.merge_adapters(p, f, _alpha, _rank))
            reg = telemetry.get_registry()
            reg.gauge("fed.lora_rank").set(_rank)
            reg.gauge("fed.lora_factor_params").set(
                lora_lib.count_factor_params(self._factors))
        # PR 9 sharded server: with run.tp_size > 1 the global model,
        # optimizer state, and aggregation live SHARDED over a local 1-D
        # (model,) mesh — the streaming fold stages per-shard slices, the
        # server update runs on sharded params, and the downlink encoder
        # reads device shards directly (comm/downlink.host_params).  When
        # the host cannot honor tp_size the fallback is counted in
        # fed.mesh_fallback_total{reason} and the coordinator runs
        # replicated exactly as before.
        from colearn_federated_learning_tpu.parallel import (
            partition as partition_lib,
        )

        self._placement = partition_lib.make_server_placement(
            params, config.run.tp_size, config.run.tp_axis,
            config.model.name,
        )
        if self._placement is not None:
            params = self._placement.shard(params)
            self._shapes_np = self._placement.shapes_tree()
        else:
            # Zero-memory shape/dtype stand-in (read-only broadcast views)
            # for folder construction and recovery templates — the round
            # loop no longer rebuilds a host params copy for them.
            self._shapes_np = jax.tree.map(
                lambda a: np.broadcast_to(
                    np.zeros((), np.dtype(getattr(a, "dtype", np.float32))),
                    np.shape(a)),
                params,
            )
        # Fold/mask shape template: the FACTOR tree under lora (the
        # uplink ships factors), the param tree otherwise.  Factor folds
        # never placement-slice — factors stay replicated server-side;
        # only the merged base is tp-sharded.
        self._fold_shapes = (jax.tree.map(np.asarray, self._factors)
                             if self._lora else self._shapes_np)
        self._fold_placement = None if self._lora else self._placement
        # --fold-device: round folds run through the fused device kernel
        # (ops/fold_kernel.py); the host fold stays the parity oracle.
        self._fold_device = bool(getattr(config.run, "fold_device", False))
        self.server_state = strategies.init_server_state(params, config.fed)
        if self._placement is not None:
            telemetry.get_registry().gauge(
                "comm.server_bytes_per_chip").set(
                    partition_lib.bytes_per_chip(self.server_state))
        self.history: list[dict] = []
        self._clients: dict[str, TensorClient] = {}
        self.trainers: list[DeviceInfo] = []
        self.evaluator: Optional[DeviceInfo] = None
        self._fail_counts: dict[str, int] = {}
        # Consecutive failed rounds → evicted (RunConfig.evict_after,
        # validated >= 1 above).
        self.evict_after = config.run.evict_after
        # One fan-out pool per coordinator lifetime (grown, never shrunk):
        # per-round ThreadPoolExecutor construction was O(cohort) thread
        # spawns on the round's critical path.
        self._pool: Optional[cf.ThreadPoolExecutor] = None
        self._pool_size = 0
        # Asks whose futures could not be cancelled after a timeout keep
        # running; they are tracked so their (already-closed) clients can
        # drain without touching a reconnected device — see _fan_out.
        self._abandoned: list[cf.Future] = []
        # Round-broadcast encoder: serialize-once, optional downlink delta
        # compression (fed.compress_down; "none" keeps the wire identical).
        self._downlink = DownlinkEncoder(config.fed.compress_down)
        # Uplink byte accounting, priced ONCE: frame lengths depend only on
        # leaf shapes/dtypes (never values), so one zeros sample gives the
        # per-update bytes a compressed uplink saves vs the dense frame —
        # the same invariant the wire bench measures against.
        self._uplink_saved_per_update = 0
        if config.fed.compress != "none" or self._lora:
            from colearn_federated_learning_tpu.fed import compression
            from colearn_federated_learning_tpu.utils.serialization import (
                wire_frame_length,
            )

            zeros = jax.tree.map(
                lambda a: np.zeros(np.shape(a), np.float32), self._shapes_np)
            dense_len = wire_frame_length(
                zeros, {"round": 0, "op": "train", "compress": "none"})
            # Under lora the update ON THE WIRE is the factor tree — the
            # savings vs a dense full-model uplink are what the record
            # (and the wire bench) price; an uplink codec composes on
            # top of the factors.
            sample = (jax.tree.map(
                lambda a: np.zeros(np.shape(a), np.float32),
                self._fold_shapes) if self._lora else zeros)
            if config.fed.compress != "none":
                wire_up, meta_up = compression.compress_delta(
                    sample, config.fed.compress,
                    topk_fraction=config.fed.topk_fraction)
                comp_len = wire_frame_length(
                    wire_up, {"round": 0, "op": "train", **meta_up})
            else:
                comp_len = wire_frame_length(
                    sample, {"round": 0, "op": "train", "compress": "none"})
            self._uplink_saved_per_update = max(0, int(dense_len - comp_len))
        self._ckpt = None
        # Round WAL rides next to the orbax checkpoint: one fsynced JSON
        # line per round (counter + accepted-update manifest), the durable
        # half of crash recovery the heavyweight state save can't cover
        # between cadence points.
        self._wal = None
        self._last_accepted: list[int] = []
        # Per-device health ledger (telemetry/health.py): durable
        # straggler attribution, gated on run.health_dir so the default
        # data path writes nothing and round records stay byte-identical.
        self.health = None
        self._health_retry_seen: dict[str, float] = {}
        if config.run.health_dir:
            self.health = telemetry.HealthLedger(config.run.health_dir,
                                                 "coordinator")
        # Convergence observatory (telemetry/convergence.py): aggregate-
        # level learning signals only — under secure aggregation the
        # server never sees an individual update, and the observatory
        # needs none.  Gated on run.learn_observe; default round records
        # stay byte-identical (pinned by test).
        self._learn = None
        if config.run.learn_observe:
            self._learn = telemetry.ConvergenceObservatory()
        # RDP accounting mirrors the engine's; each round is charged with
        # the ACTUAL cohort fraction and REALIZED noise (membership is
        # elastic here and stragglers drop mid-round).
        from colearn_federated_learning_tpu.privacy.accountant import (
            RdpAccountant,
        )

        self.accountant = RdpAccountant.from_config(config.fed,
                                                    sampling_rate=1.0)

    # ------------------------------------------------------------------
    def enroll(self, min_devices: int, timeout: float = 30.0) -> None:
        """Wait for devices, assign roles, open tensor connections.
        Every admission is appended to the durable enrollment ledger
        (when a checkpoint_dir is configured) — the record challenge-on-
        resume verifies against."""
        self._enroll.wait_for(min_devices, timeout)
        self.trainers, self.evaluator = self._enroll.assign_roles(
            want_evaluator=self.want_evaluator
        )
        for d in self.trainers + ([self.evaluator] if self.evaluator else []):
            self._clients[d.device_id] = TensorClient(
                d.host, d.port, timeout=protocol.CONNECT_TIMEOUT,
                ident=d.device_id)
            self._ledger_admit(d)

    # ---- durable enrollment + challenge-on-resume ------------------------
    def _enroll_ledger(self):
        if self._ledger is None and self.config.run.checkpoint_dir:
            from colearn_federated_learning_tpu.ckpt import EnrollmentLedger

            self._ledger = EnrollmentLedger(self.config.run.checkpoint_dir)
            # What the PREVIOUS incarnation admitted, captured before this
            # process appends anything: challenge-on-resume verifies
            # against these bindings.  The fresh appends made by this
            # process's own enroll() come straight from the replayable
            # announcements the challenge exists to distrust — verifying
            # against them would let an impostor mint its own binding.
            self._ledger_prior = self._ledger.devices()
        return self._ledger

    def _ledger_admit(self, d: DeviceInfo) -> None:
        ledger = self._enroll_ledger()
        if ledger is not None:
            ledger.admit(d)

    def verify_resumed_devices(self) -> dict:
        """Challenge-on-resume: after a resumed coordinator re-enrolls,
        readmit ONLY devices the durable ledger knows — and, when the
        ledger holds an identity pubkey for a device, only after the
        device proves possession of the matching private key (nonce echo
        under a fresh ephemeral DH pairing; `comm/keyexchange.py`).  A
        retained broker announcement alone — replayable, forgeable by
        anyone who can publish — no longer readmits anybody.  Rejected
        devices are dropped from the federation and counted in
        ``comm.enroll_challenge_rejected_total{reason}``.  Ledger entries
        without a pubkey (devices enrolled by a pre-ledger build) are
        admitted on ledger presence alone — documented trust step-down,
        closed the first time the device re-enrolls with a key."""
        import hashlib
        import os

        from colearn_federated_learning_tpu.comm import keyexchange

        ledger = self._enroll_ledger()
        reg = telemetry.get_registry()
        out = {"verified": [], "rejected": []}
        if ledger is None:
            return out
        # Verify against the bindings the PREVIOUS incarnation recorded
        # (snapshotted before this process's enroll() appended anything),
        # NOT the live ledger: the live tail was just written from the
        # very announcements the challenge distrusts.
        known = self._ledger_prior or {}
        eph_priv, eph_pub = keyexchange.generate_keypair()
        pub_s = keyexchange.encode_public(eph_pub)

        def reject(dev: DeviceInfo, reason: str) -> None:
            reg.counter("comm.enroll_challenge_rejected_total",
                        labels={"reason": reason}).inc()
            # Retract the admission this enrollment just replay-recorded,
            # so the rejected device cannot pass a FUTURE resume on it.
            ledger.revoke(dev.device_id)
            out["rejected"].append(dev.device_id)
            self.trainers = [t for t in self.trainers
                             if t.device_id != dev.device_id]
            if (self.evaluator is not None
                    and self.evaluator.device_id == dev.device_id):
                self.evaluator = None
            cli = self._clients.pop(dev.device_id, None)
            if cli is not None:
                cli.close()

        devices = list(self.trainers)
        if self.evaluator is not None:
            devices.append(self.evaluator)
        for dev in devices:
            rec = known.get(str(dev.device_id))
            if rec is None:
                reject(dev, "not_in_ledger")
                continue
            pubkey = rec.get("pubkey", "")
            if not pubkey:
                out["verified"].append(dev.device_id)
                continue
            nonce = os.urandom(16).hex()
            try:
                secret = keyexchange.shared_secret(
                    eph_priv, keyexchange.decode_public(pubkey))
            except ValueError:
                reject(dev, "bad_ledger_key")
                continue
            expect = hashlib.sha256(
                secret + bytes.fromhex(nonce)).hexdigest()
            try:
                header, _ = self._clients[dev.device_id].request(
                    {"op": "challenge", "nonce": nonce, "pub": pub_s},
                    timeout=self.round_timeout,
                )
                tag = (header.get("meta") or {}).get("tag", "")
            except (OSError, protocol.ConnectionClosed, TimeoutError):
                reject(dev, "unreachable")
                continue
            if header.get("status") != "ok" or tag != expect:
                # Forged announcement: whoever answered does not hold the
                # private key the ledger bound this device_id to.
                reject(dev, "bad_tag")
                continue
            out["verified"].append(dev.device_id)
        return out

    # ---- aggregator tier (comm/aggregator.py) ----------------------------
    def enroll_aggregators(self, n: Optional[int] = None,
                           timeout: float = 30.0) -> list[int]:
        """Discover ``n`` live aggregators from their retained announce
        records and open tensor connections to them.  Raises
        ``TimeoutError`` when fewer than ``n`` announce in time."""
        from colearn_federated_learning_tpu.comm import aggregator as agg_lib

        n = self.num_aggregators if n is None else int(n)
        if self._agg_sub is None:
            self._agg_sub = BrokerClient(self._broker_addr[0],
                                         self._broker_addr[1],
                                         timeout=protocol.CONNECT_TIMEOUT)
            self._agg_sub.subscribe(agg_lib.AGG_TOPIC + "#")
        deadline = time.monotonic() + timeout
        while True:
            agg_lib.fetch_aggregators(self._agg_sub, self._aggs,
                                      drain_timeout=0.2)
            if len(self._aggs) >= n:
                break
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"only {len(self._aggs)}/{n} aggregators announced "
                    f"within {timeout:.0f}s"
                )
        for agg_id in sorted(self._aggs):
            self._agg_connect(agg_id)
        return sorted(self._aggs)

    def _agg_connect(self, agg_id: int) -> None:
        info = self._aggs[agg_id]
        old = self._agg_clients.pop(agg_id, None)
        if old is not None:
            old.close()
        try:
            self._agg_clients[agg_id] = TensorClient(
                info["host"], info["port"], timeout=protocol.CONNECT_TIMEOUT,
                ident=f"agg:{agg_id}")
        except OSError:
            telemetry.get_registry().counter(
                "comm.reconnect_failures_total").inc()

    def _live_aggregators(self) -> list[int]:
        """Aggregators whose retained heartbeat is fresher than the
        bounded detection deadline; expiries are counted."""
        from colearn_federated_learning_tpu.comm import aggregator as agg_lib

        if self._agg_sub is not None:
            try:
                agg_lib.fetch_aggregators(self._agg_sub, self._aggs,
                                          drain_timeout=0.02)
            except protocol.ConnectionClosed:
                self._agg_sub = None    # broker died; rebuilt on reconnect
        now = time.time()
        live = []
        reg = telemetry.get_registry()
        for agg_id in sorted(self._aggs):
            age = now - self._aggs[agg_id]["ts"]
            # Live tier visibility for `colearn top` / the Prometheus
            # endpoint: last-observed heartbeat age per aggregator.
            reg.gauge("comm.agg_heartbeat_age_s",
                      labels={"agg": str(agg_id)}).set(age)
            if age <= self.agg_heartbeat_timeout:
                live.append(agg_id)
            else:
                reg.counter("comm.agg_heartbeat_expired_total").inc()
        return live

    def close(self) -> None:
        for c in self._agg_clients.values():
            c.close()
        if self._agg_sub is not None:
            self._agg_sub.close()
            self._agg_sub = None
        if self._ledger is not None:
            self._ledger.close()
            self._ledger = None
        for c in self._clients.values():
            c.close()
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
        self._broker.close()
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        if self.health is not None:
            self.health.flush()
            self.health.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def refresh_membership(self, poll: float = 0.1) -> list[str]:
        """Elastic membership: admit devices that enrolled AFTER the
        initial ``enroll()``.  New devices get the trainer role (retained)
        and join the next round's sampling pool.  The reference has no
        equivalent — workers present at startup are the federation forever;
        here the broker's retained enrollments make late joiners cheap."""
        from colearn_federated_learning_tpu.comm.enrollment import (
            admit_late_joiners,
        )

        if not self._broker.alive():
            # Control-plane SPOF healed in place: a SIGKILLed-and-restarted
            # broker loses our enrollment subscription (the manager's poll
            # SWALLOWS the dead-socket error, so without this check the
            # coordinator would silently never see another announcement).
            # Workers re-announce via their own broker watchdog; the fresh
            # manager's retained-topic subscription replays them.
            self._rebuild_broker()
        try:
            admitted = admit_late_joiners(self._enroll, self._broker,
                                          self.trainers, self.evaluator,
                                          self._clients, poll)
            if admitted:
                admitted_set = set(admitted)
                for d in self.trainers:
                    if d.device_id in admitted_set:
                        self._ledger_admit(d)
            return admitted
        except (OSError, protocol.ConnectionClosed):
            # Broker died between the liveness check and the poll/publish
            # (a SIGKILL mid-recv surfaces as ConnectionClosed, not
            # OSError — the multi-process broker-kill soak hits exactly
            # this window).
            self._rebuild_broker()
            return []

    def _rebuild_broker(self) -> None:
        """Reconnect the control plane after a broker death.  Rounds keep
        running either way (training rides direct tensor connections; only
        membership refresh and DH pubkey lookups need the broker), but the
        outcome is counted, never silent."""
        reg = telemetry.get_registry()
        try:
            fresh = BrokerClient(self._broker_addr[0], self._broker_addr[1],
                                 timeout=protocol.CONNECT_TIMEOUT)
        except OSError:
            reg.counter("comm.broker_reconnects_total",
                        labels={"outcome": "failed"}).inc()
            return
        self._broker.close()
        self._broker = fresh
        self._enroll = EnrollmentManager(fresh, mud_policy=self._mud_policy,
                                         device_type=self._device_type)
        reg.counter("comm.broker_reconnects_total",
                    labels={"outcome": "ok"}).inc()

    def _note_round_outcome(self, cohort, dropped) -> list[str]:
        """Track consecutive failures; evict peers dead for
        ``evict_after`` straight rounds (failure detection, SURVEY.md §5)."""
        dropped_set = set(dropped)
        for d in cohort:
            if d.device_id in dropped_set:
                self._fail_counts[d.device_id] = (
                    self._fail_counts.get(d.device_id, 0) + 1
                )
            else:
                self._fail_counts.pop(d.device_id, None)
        evicted = [i for i, n in self._fail_counts.items()
                   if n >= self.evict_after]
        for dev_id in evicted:
            self._fail_counts.pop(dev_id, None)
            self.trainers = [t for t in self.trainers
                             if t.device_id != dev_id]
            cli = self._clients.pop(dev_id, None)
            if cli is not None:
                cli.close()
        return evicted

    def _reconnect(self, dev: DeviceInfo) -> None:
        """Replace a device's connection after a timeout: its late reply
        would otherwise desynchronise the request/reply stream.  A dead
        peer stays closed — survivable, but counted, never silent."""
        self._clients[dev.device_id].close()
        try:
            self._clients[dev.device_id] = TensorClient(
                dev.host, dev.port, timeout=protocol.CONNECT_TIMEOUT,
                ident=dev.device_id)
        except OSError:
            telemetry.get_registry().counter(
                "comm.reconnect_failures_total").inc()

    def _request(self, dev: DeviceInfo, header: dict, tree=None, meta=None,
                 deadline=None, body=None):
        """One device request under the coordinator's retry policy.  The
        per-attempt timeout is whatever remains of the shared ``deadline``
        (never more than round_timeout), so retries cannot stack past the
        round's one budget.  ``body`` is the serialize-once path: a shared
        pre-encoded frame instead of a per-request ``tree`` encode."""
        return self._clients[dev.device_id].request(
            header, tree, meta=meta, timeout=self.round_timeout,
            retry=self.retry, deadline=deadline, body=body,
        )

    def _executor(self, n: int) -> cf.ThreadPoolExecutor:
        """The persistent fan-out pool, grown to at least ``n`` workers.
        Growth replaces the pool (stdlib pools cannot resize); the old
        pool's threads finish any abandoned asks they still hold and then
        exit — shutdown(wait=False) never blocks the round."""
        if self._pool is None or self._pool_size < n:
            if self._pool is not None:
                self._pool.shutdown(wait=False)
            self._pool_size = max(1, n)
            self._pool = cf.ThreadPoolExecutor(
                max_workers=self._pool_size, thread_name_prefix="fanout")
        return self._pool

    def _fan_out(self, devs, ask, on_result=None, timeout=None):
        """Fan ``ask`` out over ``devs`` racing ONE shared deadline
        (``timeout``, default round_timeout; sequential per-future
        timeouts would stack; each ask's retries are budgeted against the
        same deadline).

        Replies are consumed AS THEY ARRIVE (``cf.as_completed``) on this
        collector thread; ``on_result(dev, result)`` runs per arrival —
        the streaming-aggregation hook, single-threaded so folders need no
        locking.  A failed or too-slow device's socket is RECONNECTED — a
        late reply on the old socket would desynchronise the request/reply
        stream.  ``fut.cancel()`` cannot stop an ask that is already
        RUNNING, so un-cancellable futures are kept in ``_abandoned``
        (pruned once done) instead of pretending they stopped: the ask
        holds the OLD closed client, whose ``closed`` flag makes any
        retry/reconnect abort instead of touching the replacement
        connection.  Returns (results, failed_devices), ``failed`` in
        ``devs`` order."""
        self._abandoned = [f for f in self._abandoned if not f.done()]
        budget = self.round_timeout if timeout is None else timeout
        results, failed_ids, handled = [], set(), set()
        deadline = time.monotonic() + budget
        pool = self._executor(len(devs))
        futs = {pool.submit(ask, d, deadline): d for d in devs}  # colearn: hot

        def take(fut, dev):
            handled.add(fut)
            try:
                res = fut.result()
            except Exception:
                failed_ids.add(dev.device_id)
                self._reconnect(dev)
                return
            if on_result is not None:
                on_result(dev, res)
            results.append(res)

        try:
            for fut in cf.as_completed(futs, timeout=budget):
                take(fut, futs[fut])
        except cf.TimeoutError:   # colearn: noqa(CL003): stragglers dropped/counted/reconnected below
            pass  # stragglers handled below: dropped, counted, reconnected
        for fut, dev in futs.items():
            if fut in handled:
                continue
            if fut.done():
                # Completed in the race window after as_completed gave up;
                # its reply is here, so use it (same leniency the old
                # barrier's fut.result(timeout=0) had for done futures).
                take(fut, dev)
                continue
            if not fut.cancel():
                self._abandoned.append(fut)
            failed_ids.add(dev.device_id)
            self._reconnect(dev)
        failed = [d for d in devs if d.device_id in failed_ids]
        return results, failed

    def _sample_cohort(self, round_idx: int) -> list[DeviceInfo]:
        k = self.config.fed.cohort_size
        if not k or k >= len(self.trainers):
            return list(self.trainers)
        rng = np.random.default_rng(self.config.run.seed * 100_003 + round_idx)
        idx = rng.choice(len(self.trainers), size=k, replace=False)
        return [self.trainers[i] for i in sorted(idx)]

    def run_round(self) -> dict:
        """One federated round: broadcast → parallel local training with a
        deadline → weighted aggregation of the updates that made it.

        With ``secure_agg`` the train request carries the round COHORT so
        each worker can mask against its pairing partners; if any cohort
        member drops, a follow-up ``unmask`` round collects the survivors'
        orphaned mask halves (Bonawitz-pattern dropout recovery) before
        the aggregate is usable."""
        r = len(self.history)
        reg = telemetry.get_registry()
        retries_before = reg.counter("comm.retry_total").value
        with self.tracer.span("round", round=r) as round_sp:
            rec = self._run_round_traced(r)
        rec["round_time_s"] = round_sp.duration_s
        retries = reg.counter("comm.retry_total").value - retries_before
        if retries:
            # Only recorded when nonzero: an idle retry layer leaves the
            # round record byte-identical to a build without it.
            rec["retries"] = int(retries)
        reg.counter("fed.rounds_total").inc()
        reg.counter("fed.clients_dropped").inc(len(rec["dropped"]))
        reg.counter("fed.clients_evicted").inc(len(rec["evicted"]))
        reg.histogram("fed.round_time_s").observe(rec["round_time_s"])
        # Per-phase latency as labeled children of one family — the
        # labeled-summary rendering on /metrics breaks a round down
        # without a trace file.
        for phase, key in (("broadcast_collect", "phase_broadcast_collect_s"),
                           ("aggregate", "phase_aggregate_s"),
                           ("agg_fold", "phase_agg_fold_s")):
            if key in rec:
                reg.histogram("fed.phase_time_s",
                              labels={"phase": phase}).observe(rec[key])
        self.history.append(rec)
        return rec

    def _run_round_traced(self, r: int) -> dict:
        cohort = self._sample_cohort(r)
        cohort_full = list(cohort)
        # The thread-local round span context, captured HERE because the
        # fan-out asks run on pool threads where it is not implicit.
        ctx = self.tracer.current_context()
        round_t0 = time.monotonic()
        secure = self.config.fed.secure_agg
        dh = secure and self.config.fed.secure_agg_key_exchange == "dh"
        tree_mode = self.num_aggregators > 0
        share_info = None
        pruned: list[str] = []
        slices_full: list[list[DeviceInfo]] = []
        cohort_of = None
        if tree_mode:
            # Slice layout is fixed over the SAMPLED cohort, before any
            # share-phase pruning, so the pairing cohort each device sees
            # at share_setup matches its slice at train time the same way
            # the flat path's pre-prune cohort does.  Group-local masking
            # aligned to slices: every mask pair lives inside ONE
            # aggregator's partial, which therefore stays unopenable.
            from colearn_federated_learning_tpu.comm import (
                aggregator as agg_lib,
            )

            # Health-driven assignment: with a ledger attached, the
            # cohort is ranked by straggler score before the contiguous
            # split, so chronic stragglers concentrate in the LAST
            # slices instead of poisoning every slice's fold cadence.
            # Without a ledger (default) this IS slice_cohort, and the
            # round records stay byte-identical.
            scores = None
            if self.health is not None:
                fleet_now = self.health.devices()
                if fleet_now:
                    scores = {str(d): h.score()
                              for d, h in fleet_now.items()}
            slices_full = agg_lib.assign_slices(
                cohort, self.num_aggregators, scores=scores)
            if secure:
                cohort_of = {}
                for sl in slices_full:
                    ids = sorted(int(d.device_id) for d in sl)
                    for d in sl:
                        cohort_of[d.device_id] = ids
        if dh:
            # Phase 1 of the dropout-tolerant round: every cohort member
            # distributes this round's recovery shares BEFORE any mask is
            # committed.  Members that miss the share deadline are pruned
            # from the cohort — they never mask, so their death can never
            # orphan a mask half (privacy/dropout.py).
            with self.tracer.span("share_setup", cohort=len(cohort)):
                share_info, share_failed = self._share_phase(
                    r, cohort, ctx, cohort_of=cohort_of)
            if share_failed:
                pruned = [d.device_id for d in share_failed]
                cut = set(pruned)
                cohort = [d for d in cohort if d.device_id not in cut]
        with self.tracer.span("serialize_params"):  # colearn: hot
            # ONE encode + crc for the whole cohort (serialize-once): every
            # send below shares this read-only frame.  With compress_down
            # the frame is the server delta; ``resync_body`` lazily encodes
            # full params for workers whose cache missed the delta's base.
            # The encoder reads (possibly sharded) params via PER-SHARD
            # host reads — no full-tree gather on this path (CL012).
            if self._lora:
                # Composite broadcast (base + this cycle's factors), one
                # encode shared by every send.  The DownlinkEncoder's
                # delta-cache protocol is bypassed — compress_down is
                # rejected under lora (validate_robustness) — and the
                # ``lora`` meta marker tells the aggregator tier to fold
                # FACTOR-shaped replies.
                body, resync_body, saved = self._encode_lora_round(r)
            else:
                body, resync_body, saved = self._downlink.encode_round(
                    r, self.server_state.params)
        cohort_ids = sorted(int(d.device_id) for d in cohort)
        reg = telemetry.get_registry()

        from colearn_federated_learning_tpu.comm.aggregation import (
            StreamingFolder,
        )

        stale: list[str] = []
        tree_stats: Optional[dict] = None
        if tree_mode:
            # Survivors of the share phase, still grouped by the ORIGINAL
            # slice layout (pairing cohorts were fixed pre-prune).
            alive = {d.device_id for d in cohort}
            slices = [[d for d in sl if d.device_id in alive]
                      for sl in slices_full]
            # The root folds one partial per slice; the slice-keyed order
            # regroups the float sum exactly like the flat fold with
            # ``slices=`` (see aggregator.py module docstring on parity).
            folder = StreamingFolder(
                self._fold_shapes,
                order=[f"slice:{i}" for i in range(len(slices))],
                placement=self._fold_placement,
                device_fold=self._fold_device)
            with self.tracer.span("broadcast_collect",
                                  cohort=len(cohort)) as collect_sp:
                train_timeout = max(1.0, self.round_timeout
                                    - (time.monotonic() - round_t0))
                tree_stats = self._tree_collect(
                    r, slices, body, share_info, folder, train_timeout,
                    secure, stale, ctx)
            dropped = pruned + tree_stats["failed"]
        else:
            def train_req(dev: DeviceInfo):
                req = protocol.attach_trace({"op": "train", "round": r}, ctx)
                if secure:
                    req["cohort"] = cohort_ids
                if share_info is not None:
                    # This device's inbox of peer share ciphertexts rides
                    # the (per-device) request header; the broadcast body
                    # itself stays the shared serialize-once frame.
                    inbox = share_info["to"].get(dev.device_id)
                    if inbox:
                        req["shares_in"] = inbox
                return req

            def ask(dev: DeviceInfo, deadline: float):
                header, delta = self._request(dev, train_req(dev), body=body,
                                              deadline=deadline)
                if (header.get("status") == "resync"
                        and resync_body is not None):
                    # Cache miss on the worker (restart / skipped round):
                    # pay one full-params send for THIS device; the rest
                    # of the cohort keeps the compressed frame.
                    reg.counter("comm.resync_total").inc()
                    header, delta = self._request(dev, train_req(dev),
                                                  body=resync_body(),
                                                  deadline=deadline)
                elif saved:
                    reg.counter("comm.bytes_saved_downlink").inc(saved)
                if header.get("status") != "ok":
                    raise RuntimeError(
                        f"{dev.device_id}: {header.get('error')}")
                if self._uplink_saved_per_update:
                    reg.counter("comm.bytes_saved_uplink").inc(
                        self._uplink_saved_per_update)
                return header["meta"], delta

            # Fold order (hence every float sum) is pinned to COHORT order
            # by the StreamingFolder regardless of reply timing, so
            # streaming changes round records not at all — see
            # StreamingFolder docstring.
            folder = StreamingFolder(
                self._fold_shapes,
                order=[str(int(d.device_id)) for d in cohort],
                placement=self._fold_placement,
                device_fold=self._fold_device)

            def fold(dev: DeviceInfo, res) -> None:
                meta, delta = res
                if self.health is not None:
                    # Observed per-device round latency, read from the
                    # worker's own train span BEFORE it is popped.
                    self._health_note_worker(meta, r)
                _pop_worker_spans(meta, self.tracer)
                if int(meta.get("round", r)) != r:   # stale update: refuse
                    stale.append(str(meta.get("client_id")))
                    return
                folder.add(meta, delta)

            with self.tracer.span("broadcast_collect",
                                  cohort=len(cohort)) as collect_sp:
                # The train fan-out races what REMAINS of the round budget
                # after the share phase — pruning late maskers must not
                # stretch the round past its one deadline.
                train_timeout = max(1.0, self.round_timeout
                                    - (time.monotonic() - round_t0))
                results, failed = self._fan_out(cohort, ask, on_result=fold,
                                                timeout=train_timeout)
            dropped = pruned + [d.device_id for d in failed]

        with self.tracer.span("aggregate") as agg_sp:
            folder.finalize()
            if stale:
                # Deterministic order for the record: cohort position, not
                # reply-arrival order.
                pos = {str(int(d.device_id)): i
                       for i, d in enumerate(cohort)}
                dropped.extend(sorted(stale,
                                      key=lambda c: pos.get(c, len(pos))))
            # Tree mode: folded_ids are slice keys; device membership
            # comes from the partial metas (slice order, so deterministic).
            received = (tree_stats["received"] if tree_mode
                        else [int(c) for c in folder.folded_ids])
            folded = folder.count
            # Accepted-update manifest for the round WAL (crash recovery);
            # deliberately NOT part of the round record, whose byte layout
            # is contract-tested.
            self._last_accepted = received

            # Aggregation quorum: a sub-quorum round is an explicit no-op
            # (the secure-agg discarded-round convention) rather than a
            # two-survivor average passed off as progress.  0 disables.
            # Judged against the NOMINAL sampled cohort — share-phase
            # pruning must not shrink the bar it is measured by.
            quorum = (max(1, math.ceil(self.min_cohort_fraction
                                       * len(cohort_full)))
                      if self.min_cohort_fraction > 0 else 0)
            skipped_quorum = bool(quorum) and folded < quorum

            missing = sorted(set(cohort_ids) - set(received))
            unmask_failed = False
            if secure and folded and not skipped_quorum and (dh or missing):
                # Masks pair within a GROUP: the whole cohort flat, or one
                # aggregator slice in tree mode (group-local masking).
                # Each group with any folded member gets its own recovery
                # pass; a fully-dropped slice orphans no mask halves, so
                # it needs none.
                if tree_mode:
                    groups = [(ids, recv) for ids, recv
                              in zip(tree_stats["slice_ids"],
                                     tree_stats["slice_received"])
                              if recv]
                else:
                    groups = [(cohort_ids, received)]
                with self.tracer.span("unmask", dropped=len(missing)):
                    for g_ids, g_recv in groups:
                        g_miss = sorted(set(g_ids) - set(g_recv))
                        if dh:
                            # Share-based recovery runs EVERY dh round:
                            # folded clients' self-masks must come off even
                            # when nobody dropped (privacy/dropout.py
                            # double-mask).
                            ok = self._recover_dh(r, g_ids, g_recv, g_miss,
                                                  folder, share_info)
                        elif g_miss:
                            ok = self._recover_shared_seed(
                                r, g_ids, g_recv, g_miss, folder)
                        else:
                            ok = True
                        if not ok:
                            unmask_failed = True
                            break
            mean_delta, total_w, mean_loss = folder.mean()
            if skipped_quorum:
                telemetry.get_registry().counter(
                    "fed.rounds_skipped_quorum").inc()
                mean_delta = None
                mean_loss = float("nan")
            if unmask_failed:
                # Orphaned mask halves would corrupt the aggregate; a
                # no-op round is the safe failure (same convention as
                # zero weight).
                mean_delta = None
                mean_loss = float("nan")
            if secure:
                # Workers omit per-client losses under secure aggregation
                # (the per-client statistic is what the masks hide).
                mean_loss = float("nan")
            lora_merged = False
            if mean_delta is not None:
                if self._lora:
                    lora_merged = self._apply_lora_update(mean_delta)
                else:
                    self.server_state = strategies.server_update(
                        self.server_state, mean_delta, self.config.fed
                    )
            conv_sig = None
            if self._learn is not None:
                # Learning-health signals from the (possibly factor-tree)
                # aggregate; a no-op round (quorum skip / unmask failure)
                # observes nothing and leaves the trend state untouched.
                conv_sig = self._learn.observe(
                    mean_delta, lr=self.config.fed.server_lr)
                if conv_sig:
                    agg_sp.attrs["conv_update_norm"] = (
                        conv_sig["conv_update_norm"])
                    agg_sp.attrs["conv_trend"] = conv_sig["conv_trend"]
                    if "conv_cos_prev" in conv_sig:
                        agg_sp.attrs["conv_cos_prev"] = (
                            conv_sig["conv_cos_prev"])
                    self._learn.export_metrics(telemetry.get_registry(),
                                               conv_sig)
        evicted = self._note_round_outcome(cohort_full, dropped)
        rec = {
            "round": r,
            "completed": folded,
            "cohort": len(cohort_full),
            "dropped": dropped,
            "evicted": evicted,
            "train_loss": mean_loss,
            "total_weight": total_w,
            "phase_broadcast_collect_s": collect_sp.duration_s,
            "phase_aggregate_s": agg_sp.duration_s,
            # Decompress/convert/scale time the streaming fold overlapped
            # with stragglers — work that used to run AFTER the barrier.
            "phase_fold_overlap_s": folder.fold_s,
        }
        if secure:
            rec["unmask_failed"] = unmask_failed
        if quorum:
            # Key only present when the quorum feature is on, so default
            # round records stay byte-identical.
            rec["skipped_quorum"] = skipped_quorum
        if self.config.fed.compress != "none" or self._lora:
            # Uplink fast-path accounting; keys only present when an
            # uplink codec (or the adapter plane) is on — default round
            # records stay byte-identical.
            rec["bytes_saved_uplink"] = (self._uplink_saved_per_update
                                         * folded)
            rec["uplink_densify_avoided"] = folder.densify_avoided
        if self._lora:
            rec["lora_merged"] = lora_merged
        if tree_mode:
            rec["aggregators"] = self.num_aggregators
            # Middle-tier wall time (slowest slice fold — slices run
            # concurrently, so this is the tier's critical path): the
            # per-tier phase breakdown PERF.md tabulates.
            rec["phase_agg_fold_s"] = tree_stats["fold_wall_s"]
            if tree_stats["failovers"]:
                # Conditional key (nonzero only): the agg chaos soak
                # asserts on it, default tree records stay byte-stable.
                rec["agg_failovers"] = tree_stats["failovers"]
        if self.accountant is not None:
            # Workers calibrate per-client noise to the NOMINAL cohort
            # (fed/setup.py finalize_client_delta), so with only ``folded``
            # contributors the realized central noise is
            # σ·C·sqrt(folded/nominal) — charge THAT, not nominal σ, or ε
            # under-reports whenever enrollment or completion falls short.
            # A round that released no aggregate (folded == 0, or a
            # discarded unmask failure, or a sub-quorum skip) costs
            # nothing.
            if (folded > 0 and not (secure and unmask_failed)
                    and not skipped_quorum):
                nominal = setup_lib.dp_effective_cohort(self.config)
                sigma_eff = (self.config.fed.dp_noise_multiplier
                             * math.sqrt(min(folded, nominal) / nominal))
                q = len(cohort_full) / max(1, len(self.trainers))
                self.accountant.step(sampling_rate=q,
                                     noise_multiplier=sigma_eff)
            rec["dp_epsilon"] = self.accountant.epsilon()
            rec["dp_delta"] = self.accountant.delta
        if self.health is not None:
            fleet = self._health_round_feed(r, pruned, dropped, evicted,
                                            tree_mode, tree_stats)
            # health_* summary keys exist ONLY when the plane is on —
            # default round records stay byte-identical.
            rec.update(telemetry.health_record_keys(fleet))
        if conv_sig:
            # conv_* learning-health keys only under --learn-observe —
            # default round records stay byte-identical (pinned by test).
            rec.update(conv_sig)
        return rec

    # ---- health plane (telemetry/health.py) ------------------------------
    def _health_note_worker(self, meta: dict, r: int) -> None:
        """Per-device observed latency from the worker's own train span
        in the reply meta (flat mode; in tree mode the owning aggregator
        records its slice)."""
        for sd in meta.get(protocol.TRACE_SPANS_KEY) or []:
            if str(sd.get("name")) != "worker.train":
                continue
            did = str((sd.get("attrs") or {}).get(
                "client_id", meta.get("client_id", "")))
            if did:
                self.health.record(
                    did, round=r,
                    latency_s=float(sd.get("duration_s", 0.0)))

    def _health_round_feed(self, r: int, pruned, dropped, evicted,
                           tree_mode: bool, tree_stats) -> dict:
        """End-of-round attribution: deadline misses (tree mode feeds
        only whole-slice drops — per-device misses were recorded by the
        owning aggregator), share-phase prunes as secure-agg dropouts,
        evictions, and the transport's per-device retry deltas.  One
        durable flush per round.  Returns the MERGED fleet view — in
        tree mode the per-device latency lives in the aggregators'
        ledger files, so the round stamps and the labeled gauges read
        the whole directory, not just this process's records."""
        from colearn_federated_learning_tpu.telemetry import health as _hl

        pruned_set = set(pruned)
        miss = (tree_stats["slice_dropped"] if tree_mode
                else [d for d in dropped if d not in pruned_set])
        for did in miss:
            self.health.record(str(did), round=r, deadline_miss=1)
        for did in pruned:
            self.health.record(str(did), round=r, secure_dropout=1)
        for did in evicted:
            self.health.record(str(did), round=r, eviction=1)
        _hl.feed_transport_retries(self.health, self._health_retry_seen)
        self.health.flush()
        fleet = _hl.load_health(os.path.dirname(self.health.path))
        _hl.export_gauges(fleet)
        return fleet

    def _tree_collect(self, r: int, slices, body, share_info, folder,
                      timeout: float, secure: bool, stale: list,
                      ctx=None) -> dict:
        """Tree-mode collect: ONE fold request per cohort slice, routed
        to its assigned aggregator (slice i → live aggregator i mod N).
        Failover is slice-granular — a dead assignment (expired
        heartbeat, refused connection, SIGKILL mid-fold) re-homes the
        WHOLE slice to the next live sibling inside the round budget;
        devices simply re-train on the relayed duplicate request, which
        is deterministic, so the re-homed partial differs from the lost
        one only by fold regrouping.  Only when no sibling survives does
        the slice quorum-drop (``action="drop"``) — the weighted mean
        renormalizes automatically.  Returns per-slice bookkeeping the
        aggregate phase needs for group-local mask recovery."""
        reg = telemetry.get_registry()
        live = self._live_aggregators()
        agg_order = sorted(self._aggs)
        deadline = time.monotonic() + timeout
        slice_ids = [sorted(int(d.device_id) for d in sl) for sl in slices]

        def ask_slice(i: int, devs):
            req = protocol.attach_trace({
                "op": "fold", "round": r,
                "devices": [[int(d.device_id), d.host, d.port]
                            for d in devs],
            }, ctx)
            if secure:
                req["cohort"] = slice_ids[i]
            if share_info is not None:
                inboxes = {d.device_id: share_info["to"][d.device_id]
                           for d in devs
                           if share_info["to"].get(d.device_id)}
                if inboxes:
                    req["shares_in"] = inboxes
            assigned = agg_order[i % len(agg_order)] if agg_order else None
            candidates = (([assigned] if assigned in live else [])
                          + [a for a in live if a != assigned])
            for agg_id in candidates:
                info = self._aggs[agg_id]
                # The tier's fan-out budget is whatever REMAINS of the
                # round at THIS attempt — a re-home must not restart the
                # clock.
                req["timeout"] = max(1.0, deadline - time.monotonic())
                try:
                    # Fresh connection per attempt: slices re-homing onto
                    # the same sibling must not interleave frames on a
                    # shared socket.
                    cli = TensorClient(info["host"], info["port"],
                                       timeout=protocol.CONNECT_TIMEOUT,
                                       ident=f"agg:{agg_id}")
                except OSError:
                    protocol.count_suppressed()   # dead agg: try next host
                    continue
                try:
                    hdr, tree = cli.request(req, body=body, timeout=timeout,
                                            retry=self.retry,
                                            deadline=deadline)
                    if hdr.get("status") != "ok":
                        raise RuntimeError(
                            f"agg {agg_id}: {hdr.get('error')}")
                    return hdr["meta"], tree, agg_id != assigned
                except (OSError, protocol.ConnectionClosed, TimeoutError,
                        RuntimeError):
                    protocol.count_suppressed()   # mid-fold death: next host
                    continue
                finally:
                    cli.close()
            raise RuntimeError(f"slice {i}: no live aggregator")

        results: dict[int, tuple[dict, bool]] = {}
        work = [(i, sl) for i, sl in enumerate(slices) if sl]
        if agg_order:
            for i, sl in work:
                # Dispatch-time slice size per assigned aggregator — the
                # `colearn top` tier view's "slice" column.
                reg.gauge(
                    "comm.agg_slice_devices",
                    labels={"agg": str(agg_order[i % len(agg_order)])},
                ).set(len(sl))
        if work:
            with cf.ThreadPoolExecutor(
                    max_workers=len(work),
                    thread_name_prefix="tree-collect") as pool:
                futs = {pool.submit(ask_slice, i, sl): i for i, sl in work}
                pending = dict(futs)

                def take(fut, i):
                    try:
                        meta, tree, rehomed = fut.result()
                    except Exception:   # slice dropped: charged below
                        return
                    # Adopt the tier's spans — the aggregator's fold span
                    # plus the worker spans it harvested — into the round
                    # trace (take() runs on the MAIN thread, same as the
                    # fold below), completing the stitched timeline.
                    _pop_worker_spans(meta, self.tracer)
                    reg.counter(
                        "comm.agg_partials_folded_total",
                        labels={"agg": str(meta.get("agg_id", "?"))}).inc()
                    results[i] = (meta, rehomed)
                    # Partials fold under slice keys on the MAIN thread,
                    # arrival order immaterial (finalize re-orders).
                    folder.add_partial(
                        f"slice:{i}", float(meta.get("total_w", 0.0)),
                        tree, float(meta.get("loss_sum", 0.0)),
                        count=len(meta.get("folded_ids") or []))

                try:
                    for fut in cf.as_completed(futs, timeout=timeout):
                        take(fut, pending.pop(fut))
                except cf.TimeoutError:     # colearn: noqa(CL003): stragglers cancelled and counted below
                    pass
                for fut, i in pending.items():
                    if fut.done():
                        take(fut, i)    # race-window reply: use it
                    else:
                        fut.cancel()

        rehomes = drops = 0
        received: list[int] = []
        failed: list[str] = []
        slice_dropped: list[str] = []
        fold_walls: list[float] = []
        slice_recv: list[list[int]] = [[] for _ in slices]
        for i, sl in enumerate(slices):
            got = results.get(i)
            if got is None:
                if sl:
                    drops += 1
                    failed.extend(d.device_id for d in sl)
                    # Whole-slice loss (the aggregator died): the owning
                    # aggregator could not attribute these devices, so
                    # the root's health feed does.
                    slice_dropped.extend(d.device_id for d in sl)
                continue
            meta, rehomed = got
            if rehomed:
                rehomes += 1
            recv = [int(c) for c in meta.get("folded_ids") or []]
            slice_recv[i] = recv
            received.extend(recv)
            failed.extend(str(f) for f in meta.get("failed") or [])
            stale.extend(str(s) for s in meta.get("stale") or [])
            # Tier-side fold/decompress time overlapped with stragglers —
            # same accounting slot as the root's own streaming overlap.
            folder.fold_s += float(meta.get("fold_s", 0.0))
            folder.densify_avoided += int(meta.get("densify_avoided", 0))
            fold_walls.append(float(meta.get("fold_wall_s", 0.0)))
        if rehomes:
            reg.counter("comm.agg_failovers_total",
                        labels={"action": "rehome"}).inc(rehomes)
        if drops:
            reg.counter("comm.agg_failovers_total",
                        labels={"action": "drop"}).inc(drops)
        if self._uplink_saved_per_update and received:
            reg.counter("comm.bytes_saved_uplink").inc(
                self._uplink_saved_per_update * len(received))
        return {"received": received, "failed": failed,
                "slice_ids": slice_ids, "slice_received": slice_recv,
                "failovers": rehomes + drops,
                "slice_dropped": slice_dropped,
                # The tier's critical path: the SLOWEST slice fold's wall
                # time (slices run concurrently).
                "fold_wall_s": max(fold_walls) if fold_walls else 0.0}

    def _share_phase(self, r: int, cohort, ctx, cohort_of=None):
        """Collect every cohort member's encrypted recovery shares
        (privacy/dropout.py) under the SHARE deadline (a fraction of the
        round budget).  Returns ``(share_info, failed_devices)`` where
        ``share_info`` routes each ciphertext to its destination's train
        request and records each origin's reconstruction threshold and
        self-mask commitment.  The coordinator relays ciphertexts it
        cannot read — honest-but-curious stays honest-but-blind.

        ``cohort_of`` (tree mode) maps device_id → that device's
        group-local pairing cohort (its aggregator slice); masks then
        pair only within a slice, so each partial sum is a complete
        group whose pair masks cancel internally."""
        cohort_ids = sorted(int(d.device_id) for d in cohort)
        reg = telemetry.get_registry()

        def ask(dev: DeviceInfo, deadline: float):
            ids = (cohort_of.get(dev.device_id, cohort_ids)
                   if cohort_of else cohort_ids)
            header, _ = self._request(
                dev,
                protocol.attach_trace(
                    {"op": "share_setup", "round": r, "cohort": ids},
                    ctx),
                deadline=deadline,
            )
            if header.get("status") != "ok":
                raise RuntimeError(f"{dev.device_id}: {header.get('error')}")
            return header["meta"]

        got: dict[str, dict] = {}
        share_timeout = max(1.0,
                            self.round_timeout * self.share_timeout_fraction)
        _, failed = self._fan_out(
            cohort, ask,
            on_result=lambda dev, m: got.__setitem__(dev.device_id, m),
            timeout=share_timeout)
        info = {"t": {}, "commit": {}, "to": {}}
        total = 0
        for dev_id, meta in got.items():
            _pop_worker_spans(meta, self.tracer)
            origin = str(meta.get("client_id", dev_id))
            info["t"][origin] = int(meta.get("t", 0))
            info["commit"][origin] = str(meta.get("b_commit", ""))
            for dest, blob in (meta.get("shares") or {}).items():
                info["to"].setdefault(str(dest), {})[origin] = blob
                total += 1
        if total:
            reg.counter("privacy.shares_distributed_total").inc(total)
        return info, failed

    def _recover_dh(self, r: int, cohort_ids, received, missing,
                    folder, share_info) -> bool:
        """Share-based mask recovery (privacy/dropout.py, Bonawitz
        pattern): collect t-of-n recovery shares from the folded
        survivors, reconstruct every folded client's self-mask seed and
        every dead client's session secret, and remove the lot — self
        masks plus orphaned pair-mask halves — as ONE vectorized
        correction term on the finalized fold.  Tolerates silent
        survivors down to each origin's threshold; any reconstruction
        short of its threshold is a HARD failure (returns False, the
        round is discarded) because a sum with orphaned masks is garbage
        that must never be released."""
        import jax.numpy as jnp

        from colearn_federated_learning_tpu.comm import enrollment
        from colearn_federated_learning_tpu.comm import keyexchange
        from colearn_federated_learning_tpu.privacy import dropout
        from colearn_federated_learning_tpu.privacy import secure_agg as sa
        from colearn_federated_learning_tpu.utils import prng

        reg = telemetry.get_registry()

        def fail(stage: str) -> bool:
            reg.counter("privacy.share_recovery_failures_total",
                        labels={"stage": stage}).inc()
            return False

        by_id = {int(d.device_id): d for d in self.trainers}
        devs = [by_id[cid] for cid in received if cid in by_id]
        # Folded clients that applied a self-mask this round (their share
        # phase saw a nonempty recovery set).
        alive_masked = [u for u in received
                        if int(share_info["t"].get(str(u), 0)) > 0]
        s_shares: dict = {y: {} for y in missing}
        b_shares: dict = {u: {} for u in alive_masked}
        b_direct: dict = {}       # folded clients revealing their OWN b
        if missing or alive_masked:
            ctx = self.tracer.current_context()

            def ask(dev: DeviceInfo, deadline: float):
                header, _ = self._request(
                    dev,
                    protocol.attach_trace(
                        {"op": "unmask", "round": r, "dropped": missing,
                         "alive": alive_masked}, ctx),
                    deadline=deadline,
                )
                if header.get("status") != "ok":
                    raise RuntimeError(
                        f"{dev.device_id}: {header.get('error')}")
                return header["meta"]

            got: dict[str, dict] = {}
            self._fan_out(devs, ask,
                          on_result=lambda dev, m: got.__setitem__(
                              dev.device_id, m))
            collected = 0
            for dev in devs:
                meta = got.get(dev.device_id)
                if meta is None:
                    continue    # t-of-n: silent survivors are tolerated
                _pop_worker_spans(meta, self.tracer)
                x = int(meta["client_id"]) + 1
                for origin, val in (meta.get("s_shares") or {}).items():
                    if int(origin) in s_shares:
                        s_shares[int(origin)][x] = int(val, 16)
                        collected += 1
                for origin, val in (meta.get("b_shares") or {}).items():
                    if int(origin) in b_shares:
                        b_shares[int(origin)][x] = int(val, 16)
                        collected += 1
                if meta.get("b_self") is not None and (
                        int(meta["client_id"]) in b_shares):
                    # A folded survivor may reveal its own self-mask seed
                    # directly — security-equivalent to the t-of-n path
                    # for an ALIVE client (its peers would reconstruct the
                    # same value), and the only recovery when every
                    # share-holder was pruned before the shares shipped.
                    b_direct[int(meta["client_id"])] = int(
                        meta["b_self"], 16)
                    collected += 1
            reg.counter("privacy.shares_collected_total").inc(collected)

        keys: list = []
        signs: list = []
        # Self-mask removal for every folded client.
        for u in alive_masked:
            t_u = int(share_info["t"][str(u)])
            try:
                b = (b_direct[u] if u in b_direct
                     else dropout.reconstruct(b_shares.get(u, {}), t_u))
            except dropout.RecoveryError:
                return fail("self_mask")
            if dropout.commitment(b) != share_info["commit"].get(str(u)):
                # Enough shares arrived but they interpolate to the wrong
                # seed (corrupt share / inconsistent stash): subtracting a
                # garbage self-mask would corrupt the aggregate silently.
                return fail("self_mask_commit")
            keys.append(dropout.self_mask_key(b))
            signs.append(1.0)
        if alive_masked:
            reg.counter("privacy.self_masks_removed_total").inc(
                len(alive_masked))
        # Orphaned pair-mask halves of the dead: reconstruct each dead
        # client's session secret, verify it against its published DH key,
        # and re-derive the pair keys it shared with every folded partner.
        if missing:
            base_key = prng.experiment_key(self.config.run.seed)
            table = np.asarray(sa.partner_table(
                base_key, jnp.asarray(missing, jnp.int32),
                jnp.asarray(cohort_ids, jnp.int32),
                jnp.asarray(r, jnp.int32),
                neighbors=self.config.fed.secure_agg_neighbors,
            ))
            folded_set = set(received)
            info_cache: dict = {}
            for y, row in zip(missing, table):
                t_y = share_info["t"].get(str(y))
                if t_y is None:
                    return fail("no_share_setup")
                try:
                    s_y = dropout.reconstruct(s_shares.get(y, {}), int(t_y))
                except dropout.RecoveryError:
                    return fail("session_secret")
                try:
                    pub_y = keyexchange.decode_public(
                        enrollment.fetch_device_info(
                            self._broker, str(y), cache=info_cache).pubkey)
                except (OSError, TimeoutError, ValueError):
                    return fail("pubkey_lookup")
                if pow(keyexchange.GROUP14_G, s_y,
                       keyexchange.GROUP14_P) != pub_y:
                    # Wrong interpolation (or a tampered share): the
                    # public key is the binding check for session secrets.
                    return fail("session_secret_verify")
                partners = sorted(
                    ({int(p) for p in row.tolist()} & folded_set) - {y})
                for v in partners:
                    try:
                        pub_v = keyexchange.decode_public(
                            enrollment.fetch_device_info(
                                self._broker, str(v),
                                cache=info_cache).pubkey)
                    except (OSError, TimeoutError, ValueError):
                        return fail("pubkey_lookup")
                    secret = keyexchange.shared_secret(s_y, pub_v)
                    keys.append(np.asarray(
                        keyexchange.pair_prng_key(secret, v, y)))
                    # Survivor v folded sign(y − v)·PRG(k_vy); subtract
                    # exactly that.
                    signs.append(1.0 if y > v else -1.0)
                reg.counter("privacy.masks_recovered_total",
                            labels={"device": str(y)}).inc()
        if keys:
            template = jax.tree.map(
                lambda l: jnp.zeros(np.shape(l), jnp.float32), folder.shapes)
            correction = sa.pairwise_mask_with_keys(
                template, jnp.asarray(np.stack(keys)),
                jnp.asarray(np.asarray(signs, np.float32)),
                jnp.asarray(r, jnp.int32),
            )
            folder.apply_correction(jax.tree.map(np.asarray, correction))
        return True

    def _recover_shared_seed(self, r: int, cohort_ids, received, missing,
                             folder) -> bool:
        """Dropout recovery under the coordinator-trusted ``shared_seed``
        exchange: every pair key derives from the experiment seed this
        process already holds, so the orphaned halves are recomputed
        LOCALLY — zero survivor round-trips, immune to further survivor
        deaths.  (The privacy trade-off is the mode's, not recovery's:
        see FedConfig.secure_agg_key_exchange.)"""
        import jax.numpy as jnp

        from colearn_federated_learning_tpu.privacy import secure_agg as sa
        from colearn_federated_learning_tpu.utils import prng, pytrees

        reg = telemetry.get_registry()
        base_key = prng.experiment_key(self.config.run.seed)
        table = np.asarray(sa.partner_table(
            base_key, jnp.asarray(missing, jnp.int32),
            jnp.asarray(cohort_ids, jnp.int32), jnp.asarray(r, jnp.int32),
            neighbors=self.config.fed.secure_agg_neighbors,
        ))
        folded_set = set(received)
        template = jax.tree.map(
            lambda l: jnp.zeros(np.shape(l), jnp.float32), folder.shapes)
        correction = None
        for y, row in zip(missing, table):
            partners = sorted({int(p) for p in row.tolist()} & folded_set)
            if not partners:
                continue
            # The mask y WOULD have added is the exact negative of its
            # orphaned halves in the folded sum (sign antisymmetry).
            mask_y = sa.pairwise_mask(
                template, base_key, jnp.asarray(y, jnp.int32),
                jnp.asarray(partners, jnp.int32),
                jnp.asarray(r, jnp.int32),
            )
            neg = pytrees.tree_scale(jax.tree.map(np.asarray, mask_y), -1.0)
            correction = (neg if correction is None
                          else pytrees.tree_add(correction, neg))
            reg.counter("privacy.masks_recovered_total",
                        labels={"device": str(y)}).inc()
        if correction is not None:
            folder.apply_correction(correction)
        return True

    # ---- LoRA adapter plane (fed/lora.py) --------------------------------
    def _encode_lora_round(self, r: int):
        """Serialize-once lora broadcast: ONE frame holding the frozen
        base (read per-shard off the sharded server — no gather) plus the
        current factor tree, stamped with the ``lora`` meta marker the
        aggregator tier keys its factor-shaped fold template off.  Same
        (body, resync_body, saved) contract as DownlinkEncoder: resync
        never triggers (workers hold no delta cache under lora)."""
        from colearn_federated_learning_tpu.comm.downlink import host_params
        from colearn_federated_learning_tpu.utils.serialization import (
            pytree_to_bytes,
        )

        composite = {
            "base": host_params(self.server_state.params),
            "factors": jax.tree.map(np.asarray, self._factors),
        }
        body = pytree_to_bytes(
            composite, {"round": r, "lora": self.config.fed.lora_rank})
        telemetry.get_registry().counter("comm.broadcast_encode_total").inc()
        return memoryview(body), None, 0

    def _apply_lora_update(self, mean_delta) -> bool:
        """Fold the round's mean FACTOR delta into the server factors
        (manual FedAvg/FedProx step — adaptive server optimizers are
        rejected for lora by validate_robustness, their moment state is
        params-shaped) and, every ``lora_merge_every`` aggregations,
        merge B·A·(α/r) into the (possibly tp-sharded) base shard-wise.
        Returns True when this round merged."""
        lr = self.config.fed.server_lr
        self._factors = jax.tree.map(
            lambda f, d: f + lr * jnp.asarray(np.asarray(d), f.dtype),
            self._factors, mean_delta)
        self.server_state = self.server_state._replace(
            round_idx=self.server_state.round_idx + 1)
        self._lora_agg_count += 1
        if self._lora_agg_count < self.config.fed.lora_merge_every:
            return False
        self._merge_lora()
        return True

    def _merge_lora(self) -> None:
        """Jitted shard-wise merge: every op is elementwise in the base
        leaf plus a small replicated r-contraction, so XLA keeps each
        leaf's output in its input sharding — the bytes a replicated
        merge would have gathered are counted in
        ``comm.gather_bytes_avoided_total``.  B resets to zero (the
        merged delta now lives in the base); A is kept, so the factor
        tree's shapes — and the workers' one compile signature — never
        change."""
        from colearn_federated_learning_tpu.fed import lora as lora_lib
        from colearn_federated_learning_tpu.parallel import (
            partition as partition_lib,
        )

        reg = telemetry.get_registry()
        avoided = partition_lib.tree_gather_avoided(self.server_state.params)
        merged = self._merge_fn(self.server_state.params, self._factors)
        self.server_state = self.server_state._replace(params=merged)
        self._factors = lora_lib.reset_factors(self._factors)
        self._lora_agg_count = 0
        reg.counter("fed.lora_merges_total").inc()
        if avoided:
            reg.counter("comm.gather_bytes_avoided_total").inc(avoided)

    def _eval_params(self):
        """The model evaluation scores: under lora the UNMERGED factor
        cycle still carries signal, so a temp merge folds it in without
        touching the server base (checkpoints carry only the base — at
        most ``lora_merge_every`` rounds of factor progress ride outside
        the checkpoint, a documented limitation)."""
        params = self.server_state.params
        if self._lora:
            params = self._merge_fn(params, self._factors)
        return params

    def evaluate_per_client(self) -> dict:
        """Score the CURRENT global model on every trainer's own shard
        (the engine's ``evaluate_per_client`` over the wire): fan-out
        ``self_eval`` requests, one shared deadline; devices that fail are
        skipped.  Returns weighted aggregates plus the accuracy spread."""
        if self.config.fed.secure_agg:
            raise NotImplementedError(
                "per-client evaluation is disabled under secure_agg: "
                "per-client statistics are exactly what the masks hide"
            )
        from colearn_federated_learning_tpu.comm.downlink import host_params
        from colearn_federated_learning_tpu.utils.serialization import (
            pytree_to_bytes,
        )

        # Per-shard host read (no full-tree gather): counts the avoided
        # bytes into ``comm.gather_bytes_avoided_total``.
        params_np = host_params(self._eval_params())
        # Serialize-once here too: one shared frame for the whole fan-out.
        body = memoryview(pytree_to_bytes(params_np))
        telemetry.get_registry().counter("comm.broadcast_encode_total").inc()
        ctx = self.tracer.current_context()

        def ask(dev: DeviceInfo, deadline: float):
            header, _ = self._request(
                dev, protocol.attach_trace({"op": "self_eval"}, ctx),
                body=body, deadline=deadline,
            )
            if header.get("status") != "ok":
                raise RuntimeError(f"{dev.device_id}: {header.get('error')}")
            return header["meta"]

        # Collect per device, then summarize in trainer order — the
        # weighted sums below must not depend on reply-arrival order.
        got: dict[str, dict] = {}
        self._fan_out(self.trainers, ask,
                      on_result=lambda dev, m: got.__setitem__(
                          dev.device_id, m))
        metas = [got[d.device_id] for d in self.trainers
                 if d.device_id in got]
        for m in metas:
            _pop_worker_spans(m, self.tracer)
        if not metas:
            return {"num_clients_evaluated": 0}
        from colearn_federated_learning_tpu.fed.evaluation import (
            summarize_per_client,
        )

        out = summarize_per_client(
            [m["self_loss"] for m in metas],
            [m["self_acc"] for m in metas],
            [m["num_examples"] for m in metas],
        )
        out["num_clients_evaluated"] = len(metas)
        out["per_client"] = {m["client_id"]: m["self_acc"] for m in metas}
        return out

    def evaluate(self) -> dict:
        """Score the global model on the evaluator device (SURVEY.md §3d)."""
        if self.evaluator is None:
            raise RuntimeError("no evaluator was assigned")
        from colearn_federated_learning_tpu.comm.downlink import host_params

        params_np = host_params(self._eval_params())
        with self.tracer.span("evaluate"):
            header, _ = self._clients[self.evaluator.device_id].request(
                protocol.attach_trace({"op": "eval"},
                                      self.tracer.current_context()),
                params_np, timeout=self.round_timeout,
            )
        if header.get("status") != "ok":
            raise RuntimeError(f"evaluator failed: {header.get('error')}")
        meta = header["meta"]
        _pop_worker_spans(meta, self.tracer)
        return meta

    # ---- checkpoint/resume (same RoundCheckpointer as the engine, or the
    # shard-native StreamingCheckpointer when run.ckpt_stream is set) ------
    def _checkpointer(self):
        if self._ckpt is None:
            from colearn_federated_learning_tpu.ckpt import (
                RoundCheckpointer,
                StreamingCheckpointer,
            )

            cls = (StreamingCheckpointer if self.config.run.ckpt_stream
                   else RoundCheckpointer)
            self._ckpt = cls.for_run(self.config.run)
        return self._ckpt

    def _round_wal(self):
        if self._wal is None:
            from colearn_federated_learning_tpu.ckpt import RoundWal

            if not self.config.run.checkpoint_dir:
                raise ValueError("config.run.checkpoint_dir is not set")
            self._wal = RoundWal(self.config.run.checkpoint_dir)
        return self._wal

    def _acct_rdp(self) -> np.ndarray:
        # orbax refuses zero-size arrays, so "no accountant" is a (1,) zero.
        return (self.accountant.total_rdp if self.accountant is not None
                else np.zeros(1))

    def save_checkpoint(self) -> None:
        # The accumulated RDP vector rides along: per-round sampling rates
        # vary with membership, so ε cannot be reconstructed from a round
        # count the way the constant-mechanism engine does.
        self._checkpointer().save(
            len(self.history), (self.server_state, self._acct_rdp()),
            self.history,
        )

    def restore_checkpoint(self) -> int:
        """Restore the latest checkpoint; returns the resumed round index.
        A killed ``colearn coordinate`` run picks up exactly where it
        stopped — workers are stateless between rounds (they receive the
        global params every round), so only the coordinator's server state,
        history and privacy budget need to survive.

        WAL reconciliation: rounds logged past the restored checkpoint
        step ran but never committed their server-state delta (the crash
        landed between WAL append and state save) — they are discarded
        (``ckpt.wal_uncommitted_discarded_total``) and re-run."""
        reg = telemetry.get_registry()
        with self.tracer.span("resume"):
            state, history, step = self._checkpointer().restore(
                (self.server_state, self._acct_rdp())
            )
            self.server_state, acct_rdp = state
            if self._placement is not None:
                # Restored leaves may come back as host arrays; re-place
                # them on the server mesh so the resumed run keeps the
                # sharded fold/update/encode plane (and its bitwise
                # parity with the pre-crash rounds).
                s = self.server_state
                put = self._placement.shard
                self.server_state = type(s)(
                    params=put(s.params),
                    opt_m=put(s.opt_m) if s.opt_m is not None else None,
                    opt_v=put(s.opt_v) if s.opt_v is not None else None,
                    control=(put(s.control) if s.control is not None
                             else None),
                    round_idx=s.round_idx,
                )
            self.history = history
            if self.accountant is not None:
                self.accountant.total_rdp = np.asarray(acct_rdp)
                self.accountant._steps = step
            wal = self._round_wal()
            logged = wal.load()
            if len(logged) > step:
                reg.counter("ckpt.wal_uncommitted_discarded_total").inc(
                    len(logged) - step)
                wal.rewind(step)
        reg.counter("fed.rounds_resumed_total").inc()
        return step

    def fit(self, rounds: Optional[int] = None, log_fn=None,
            eval_every: Optional[int] = None,
            elastic: bool = False) -> list[dict]:
        """``elastic=True`` polls enrollment between rounds so late-joining
        devices are admitted mid-run.  ``rounds=None`` runs the REMAINING
        ``config.fed.rounds - len(history)`` rounds, so a restored
        coordinator finishes its original budget rather than restarting."""
        if rounds is None:
            rounds = max(0, self.config.fed.rounds - len(self.history))
        eval_every = eval_every or self.config.run.eval_every
        run = self.config.run
        ckpt_every = max(0, run.checkpoint_every)
        want_ckpt = bool(run.checkpoint_dir)
        last_round = len(self.history) + rounds - 1
        for _ in range(rounds):
            if elastic:
                self.refresh_membership()
            rec = self.run_round()
            if want_ckpt:
                # WAL first, state second: an entry past the latest
                # checkpoint step marks an uncommitted round for resume.
                self._round_wal().append({
                    "round": rec["round"],
                    "accepted": list(self._last_accepted),
                    "completed": rec["completed"],
                    "total_weight": rec["total_weight"],
                })
            if self.evaluator is not None and (
                rec["round"] % max(1, eval_every) == 0
                or rec["round"] == last_round
            ):
                rec.update(self.evaluate())
            # Checkpoint BEFORE the record is logged: a logged round is a
            # durably committed round (at the configured cadence), so a
            # kill keyed on the record line — the mp chaos harness — lands
            # on a checkpoint that exists.  With a checkpoint_dir the
            # final round always checkpoints, so --resume works without a
            # periodic cadence.
            if want_ckpt and (
                (ckpt_every and (rec["round"] + 1) % ckpt_every == 0)
                or rec["round"] == last_round
            ):
                self.save_checkpoint()
            if log_fn is not None:
                log_fn(rec)
        return self.history
