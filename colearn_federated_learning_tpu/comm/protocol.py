"""Wire framing: [4B header-len][JSON header][8B body-len][4B crc32][body].

One frame carries a JSON control header (msg type, topic, round index, …)
plus an optional opaque body (serialized model pytree — see
utils/serialization.py).  Used by both the pub/sub broker (control plane)
and the tensor transport (data plane); the reference's equivalent split is
MQTT JSON payloads + pickled-PySyft-tensor websocket frames.

Every frame carries a CRC32 over header+body, so a corrupted frame is a
:class:`CorruptFrame` at the receiver — classified per-connection (one
device's bad frame drops that device, never the coordinator) instead of
surfacing as a JSON decode error or, worse, silently folding garbage
bytes into an aggregate.

The secure-aggregation dropout protocol (privacy/dropout.py) rides this
framing untouched: ``share_setup`` / ``unmask`` requests are header-only
frames (no tensor body), and the per-device encrypted share blobs travel
as hex strings in the JSON header (``shares_in``) — which is what lets
the broadcast body stay a single shared serialize-once buffer.  At 132
bytes (264 hex chars) per share, ``MAX_HEADER`` comfortably bounds the
per-device share inbox for any socket-plane cohort.
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Optional

from colearn_federated_learning_tpu.telemetry import registry as _metrics

_HDR = struct.Struct(">I")     # header length
_BODY = struct.Struct(">QI")   # body length, crc32(header bytes + body)
MAX_HEADER = 1 << 20           # 1 MiB of JSON is already absurd
MAX_BODY = 1 << 34             # 16 GiB

TRACE_KEY = "trace"            # header slot carrying the trace context


def attach_trace(header: dict, context) -> dict:
    """Inject a tracer span context ``(trace_id, span_id)`` into a message
    header (in place), so the receiver's spans stitch under the sender's.
    A ``None`` context is a no-op — untraced senders stay untraced."""
    if context is not None:
        header[TRACE_KEY] = {"trace_id": context[0], "span_id": context[1]}
    return header


def extract_trace(header: dict):
    """Inverse of :func:`attach_trace`; returns a span context or None.
    Tolerates malformed values — a peer's bad header must degrade to an
    unstitched trace, not an error."""
    ctx = header.get(TRACE_KEY)
    if not isinstance(ctx, dict):
        return None
    trace_id, span_id = ctx.get("trace_id"), ctx.get("span_id")
    if not (isinstance(trace_id, str) and isinstance(span_id, str)):
        return None
    return (trace_id, span_id)


TRACE_SPANS_KEY = "trace_spans"  # reply-meta slot carrying worker spans


def pop_trace_spans(meta, tracer) -> None:
    """Stitch a reply's worker-side spans into the local trace and strip
    them from the metadata — they must not leak into round records or
    metrics JSONL, which consume reply metas wholesale."""
    if not isinstance(meta, dict):
        return
    spans = meta.pop(TRACE_SPANS_KEY, None)
    if spans:
        tracer.adopt(spans)


class ConnectionClosed(Exception):
    """Peer closed the socket mid-frame (or before one started)."""


class CorruptFrame(ValueError):
    """Frame failed an integrity check (length sanity or CRC32 mismatch).

    Subclasses ``ValueError`` so every existing per-connection handler
    (TensorServer._serve, broker loops) already treats it as that one
    peer's failure; the stream is unrecoverable past this point, so the
    connection must be dropped, not re-read."""


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes into one preallocated buffer.

    ``recv_into`` a sliding memoryview, so a multi-chunk body costs one
    allocation and zero reassembly copies (the old recv-and-extend loop
    reallocated and memmoved the accumulator as it grew — measurable at
    model-frame sizes).  Returns the bytearray itself; callers treat it
    as read-only bytes."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        # The per-read deadline is the caller's settimeout (BrokerClient
        # drains via a reader thread; TensorServer sets a serve timeout).
        r = sock.recv_into(view[got:], n - got)  # colearn: noqa(CL002): deadline is the caller's settimeout
        if not r:
            raise ConnectionClosed(f"peer closed after {got}/{n} bytes")
        got += r
    return buf


def frame_crc(hdr: bytes, body: bytes) -> int:
    return zlib.crc32(body, zlib.crc32(hdr))


def _corrupt(msg: str) -> CorruptFrame:
    _metrics.get_registry().counter("comm.corrupt_frames_total").inc()
    return CorruptFrame(f"corrupt frame: {msg}")


def send_msg(sock: socket.socket, header: dict, body=b"") -> None:
    """``body`` is any bytes-like object (bytes / bytearray / memoryview)
    — the coordinator passes one shared read-only frame to every cohort
    send (serialize-once broadcast), so this must never copy it."""
    hdr = json.dumps(header, separators=(",", ":")).encode()
    if len(hdr) > MAX_HEADER:
        raise ValueError(f"header too large: {len(hdr)}")
    prefix = (_HDR.pack(len(hdr)) + hdr
              + _BODY.pack(len(body), frame_crc(hdr, body)))
    if body:
        # One vectored syscall for prefix+body instead of two sendalls
        # (saves a syscall + a small-segment wakeup per message).  sendmsg
        # may send partially; finish the tail with sendall on views.
        sent = sock.sendmsg([prefix, body])
        total = len(prefix) + len(body)
        if sent < total:
            if sent < len(prefix):
                sock.sendall(memoryview(prefix)[sent:])
                sock.sendall(body)
            else:
                sock.sendall(memoryview(body)[sent - len(prefix):])
    else:
        sock.sendall(prefix)
    reg = _metrics.get_registry()
    reg.counter("comm.messages_sent").inc()
    reg.counter("comm.bytes_sent").inc(
        _HDR.size + len(hdr) + _BODY.size + len(body)
    )


def recv_msg(sock: socket.socket) -> tuple[dict, bytes]:
    (hlen,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if hlen > MAX_HEADER:
        raise _corrupt(f"header length {hlen}")
    hdr = _recv_exact(sock, hlen)
    (blen, crc) = _BODY.unpack(_recv_exact(sock, _BODY.size))
    if blen > MAX_BODY:
        raise _corrupt(f"body length {blen}")
    body = _recv_exact(sock, blen) if blen else b""
    if frame_crc(hdr, body) != crc:
        raise _corrupt("crc32 mismatch")
    try:
        header = json.loads(hdr.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        # CRC passed but the header is not JSON: a buggy (not flaky) peer.
        raise _corrupt(f"undecodable header ({e})") from None
    reg = _metrics.get_registry()
    reg.counter("comm.messages_received").inc()
    reg.counter("comm.bytes_received").inc(
        _HDR.size + hlen + _BODY.size + blen
    )
    return header, body


# Default budget for control-plane connection establishment: generous
# against slow brokers, finite against dead ones (CL002 contract).
CONNECT_TIMEOUT = 10.0


def connect(host: str, port: int, timeout: Optional[float] = None) -> socket.socket:
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def count_suppressed(n: int = 1) -> None:
    """Record an intentionally-suppressed teardown error — survivable but
    never silent (CL003 contract)."""
    _metrics.get_registry().counter("comm.suppressed_oserrors_total").inc(n)


def close_quietly(sock: socket.socket, shutdown: bool = False) -> None:
    """Teardown close: OSErrors are expected here (the peer may already be
    gone) and are counted in ``comm.suppressed_oserrors_total`` instead of
    swallowed.  ``shutdown=True`` also shuts the stream down first — see
    MessageBroker.stop for why close() alone cannot unblock a reader."""
    if shutdown:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            count_suppressed()
    try:
        sock.close()
    except OSError:
        count_suppressed()


def wake_accept(host: str, port: int, timeout: float = 1.0) -> None:
    """Unblock a thread stuck in ``accept(2)`` on (host, port).

    On Linux, closing a listening socket from another thread does NOT
    interrupt an in-progress accept syscall (the kernel holds the file
    reference until it returns), which would leave the LISTEN socket
    alive and the port occupied.  A throwaway connection forces accept to
    return; callers set their stop flag FIRST so the accept loop exits,
    and pass their own shutdown ``timeout`` budget.  Shared by
    MessageBroker.stop and TensorServer.stop.  A failed wake connect is
    survivable (the listener may already be gone) but never silent: it is
    counted in ``comm.suppressed_oserrors_total``."""
    try:
        wake = socket.create_connection((host, port), timeout=timeout)
        wake.close()
    except OSError:
        _metrics.get_registry().counter(
            "comm.suppressed_oserrors_total").inc()
