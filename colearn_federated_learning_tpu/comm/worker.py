"""Device worker: one federated participant as a network service.

The reference's client runtime is a PySyft ``WebsocketServerWorker`` that
hosts a data shard, receives the global model, runs local PyTorch epochs
and returns weights (SURVEY.md §3b/§3c).  Here the worker hosts its
partition slice and a jit-compiled ``lax.scan`` local trainer
(fed/local.py via fed/setup.py — the SAME trainer the on-device simulation
vmaps), serves ``train`` / ``eval`` requests over the tensor plane, and
enrolls itself on the control plane.

Requests:
  {"op": "train", "round": r[, "cohort"][, "shares_in"]} + params
                                       →  delta + meta{weight,...}
  {"op": "share_setup", "round", "cohort"} → meta{shares, t, b_commit}:
                                           this round's Shamir shares of the
                                           session DH secret + a fresh
                                           self-mask seed, one ciphertext
                                           per recovery-set peer (relayed
                                           opaquely by the coordinator)
  {"op": "eval"}      + global params  →  meta{eval_loss, eval_acc}
  {"op": "self_eval"} + global params  →  meta{self_loss, self_acc, ...}
                                           (disabled under secure_agg)
  {"op": "unmask", "round", "dropped", "alive"} → recovery shares: session-
                                           secret shares for the dead,
                                           self-mask shares for the folded
                                           (never both per origin)
  {"op": "unmask", "round", "dropped", "cohort"} → legacy direct form:
                                           summed pair masks vs the dropped
                                           peers this client paired with
  {"op": "info"}                       →  meta{num_examples, ...}
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.comm.broker import BrokerClient
from colearn_federated_learning_tpu.comm import downlink
from colearn_federated_learning_tpu.comm import enrollment
from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.comm.transport import TensorServer
from colearn_federated_learning_tpu.telemetry import Tracer
from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.data.sharding import pack_client_shards
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.utils import prng
from colearn_federated_learning_tpu.utils.config import ExperimentConfig


class DeviceWorker:
    """One device process/thread: local shard + trainer + tensor server."""

    def __init__(
        self,
        config: ExperimentConfig,
        client_id: int,
        broker_host: Optional[str] = None,
        broker_port: Optional[int] = None,
        dataset: Optional[data_registry.Dataset] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        mud_profile: Optional[str] = None,
    ):
        """``mud_profile``: RFC 8520 MUD JSON text announced on the
        enrollment record (comm/mud.py) — the CoLearn device identity a
        coordinator's MudPolicy gates admission on."""
        self.config = config
        self.client_id = int(client_id)
        c = config
        setup_lib.require_stateless_strategy(c, "the socket worker")
        if c.fed.secure_agg and c.fed.secure_agg_neighbors and (
            c.fed.secure_agg_neighbors % 2 or c.fed.secure_agg_neighbors < 2
        ):
            raise ValueError(
                "secure_agg_neighbors must be an even integer >= 2, got "
                f"{c.fed.secure_agg_neighbors}"
            )
        if c.fed.secure_agg and c.fed.compress != "none":
            raise ValueError(
                "secure_agg over the wire cannot compress: masked updates "
                "are dense gaussian-scale payloads, and lossy compression "
                "would break the pairwise mask cancellation"
            )
        if c.fed.secure_agg and c.fed.compress_feedback:
            raise ValueError(
                "secure_agg cannot carry uplink error feedback: masked "
                "updates are dense by construction, so there is no "
                "compression residual to feed back"
            )
        if c.fed.secure_agg_key_exchange not in ("dh", "shared_seed"):
            raise ValueError(
                "secure_agg_key_exchange must be 'dh' or 'shared_seed', "
                f"got {c.fed.secure_agg_key_exchange!r}"
            )
        if c.fed.secure_agg and not (
            0.0 < c.fed.secure_agg_threshold <= 1.0
        ):
            raise ValueError(
                "secure_agg_threshold must be in (0, 1], got "
                f"{c.fed.secure_agg_threshold}"
            )
        self._dh_mode = (c.fed.secure_agg
                         and c.fed.secure_agg_key_exchange == "dh")
        if self._dh_mode:
            if broker_host is None:
                raise ValueError(
                    "secure_agg with key_exchange='dh' needs the broker "
                    "control plane to distribute public keys; pass "
                    "secure_agg_key_exchange='shared_seed' ONLY if you "
                    "trust the coordinator with every pair key"
                )
            from colearn_federated_learning_tpu.comm import keyexchange

            self._dh_priv, self._dh_pub = keyexchange.generate_keypair()
            self._dh_lock = threading.Lock()
            self._dh_lookup = None        # dedicated broker connection
            self._dh_stopped = False
            self._peer_info_cache: dict = {}   # cleared each round
            # id -> (pubkey_str, pair key uint32[2], raw DH secret bytes)
            self._peer_keys: dict = {}
            self._peer_round: Optional[int] = None
            # Dropout-recovery state (privacy/dropout.py): per-round
            # self-mask seeds, decrypted incoming shares keyed
            # (round, origin), and the reveal-exclusivity ledger — at most
            # ONE of {self-mask share, session-secret share} is ever
            # revealed per (round, origin), or the coordinator could
            # unmask a folded client it falsely reported dead.
            self._round_secrets: dict = {}     # round -> b_u | None
            self._incoming_shares: dict = {}   # (round, origin) -> (s, b)
            self._revealed: dict = {}          # (round, origin) -> "s"|"b"

        # Always-on identity keypair: the announced pubkey is the identity
        # the coordinator's durable enrollment ledger binds this device_id
        # to, and challenge-on-resume proves possession of the private
        # half (ckpt/wal.py EnrollmentLedger).  In dh mode the secure-agg
        # session keypair doubles as the identity; otherwise one is
        # generated purely for identity — either way every announce now
        # carries a key, so the ledger never records a keyless device.
        if self._dh_mode:
            self._id_priv, self._id_pub = self._dh_priv, self._dh_pub
        else:
            from colearn_federated_learning_tpu.comm import keyexchange

            self._id_priv, self._id_pub = keyexchange.generate_keypair()

        ds = dataset or data_registry.get_dataset(c.data.dataset,
                                                  seed=c.run.seed)
        self._dataset = ds
        labels = np.asarray(ds.y_train)
        parts = setup_lib.partition_for_config(c, labels)
        if not 0 <= self.client_id < len(parts):
            raise ValueError(
                f"client_id {self.client_id} out of range [0, {len(parts)})"
            )
        shard = pack_client_shards(
            np.asarray(ds.x_train), labels, [parts[self.client_id]],
            capacity=c.data.max_examples_per_client,
        )
        self._x = jnp.asarray(shard.x[0])
        self._y = jnp.asarray(shard.y[0])
        self._count = jnp.asarray(shard.counts[0])
        self.num_examples = int(shard.counts[0])

        model = model_registry.build_model(setup_lib.local_model_config(c.model))
        self._lora = c.fed.lora_rank > 0
        if self._lora:
            # Factor-only trainer (fed/local.py make_lora_local_update):
            # broadcasts arrive as a {"base", "factors"} composite, the
            # base stays frozen, and the reply delta is the O(r·d)
            # factor tree.  One jitted signature, same as the dense path.
            lora_update, self._num_steps = setup_lib.lora_trainer_for_config(
                c, model.apply, shard.capacity
            )
            self._update_fn = jax.jit(lora_update)
        else:
            local_update, self._num_steps = setup_lib.local_trainer_for_config(
                c, model.apply, shard.capacity
            )
            self._update_fn = jax.jit(local_update)
        self._model = model
        self._eval_fn = None          # built on first eval request
        self._key = prng.experiment_key(c.run.seed)

        # Span tracer for this device.  Recording into the local buffer
        # stays OFF (a long-lived worker must not grow a span log); each
        # traced request's spans are captured per-thread and shipped back
        # in the reply metadata, where the coordinator stitches them into
        # its trace via the propagated trace id.
        self.tracer = Tracer(process=f"worker-{self.client_id}",
                             enabled=False)
        self._server = TensorServer(self._handle, host=host, port=port,
                                    ident=str(self.client_id))
        self._broker: Optional[BrokerClient] = None
        self._broker_addr = (broker_host, broker_port)
        self._mud_profile = mud_profile or ""
        self.role: Optional[str] = None
        self._watch_stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        # Last-applied global params, engaged the first time a broadcast
        # carries a downlink mode (coordinator runs compress_down).
        self._param_cache: Optional[downlink.WorkerParamCache] = None
        # Uplink error-feedback residual (fed.compress_feedback): what the
        # last round's codec dropped, carried into the next delta before
        # compression — symmetric to the downlink encoder's
        # reconstruction-base feedback.  None until the first lossy
        # compress; reset on resync/param-cache miss (a stale residual
        # belongs to an update the server never folded).
        self._uplink_residual: Optional[Any] = None
        # Adaptive topk density (fed.topk_adaptive): per-round effective
        # fraction steered off the residual norm trend, clipped to the
        # config's [topk_min_fraction, topk_max_fraction] band.
        self._topk_fraction = float(c.fed.topk_fraction)
        if getattr(c.fed, "topk_adaptive", False):
            self._topk_fraction = min(
                float(c.fed.topk_max_fraction),
                max(float(c.fed.topk_min_fraction), self._topk_fraction))
        self._last_residual_norm: Optional[float] = None

    # ------------------------------------------------------------------
    @property
    def port(self) -> int:
        return self._server.port

    @property
    def host(self) -> str:
        return self._server.host

    def start(self) -> "DeviceWorker":
        """Start serving; if a broker address was given, enroll there and
        start the re-enrollment watchdog (a restarted broker loses this
        device's retained enrollment — the watchdog reconnects and
        re-announces so the federation heals without operator action)."""
        self._server.start()
        bh, bp = self._broker_addr
        if bh is not None:
            self._broker = BrokerClient(bh, bp,
                                        timeout=protocol.CONNECT_TIMEOUT)
            self._announce(self._broker)
            self._watchdog = threading.Thread(
                target=self._watch_broker,
                name=f"worker-{self.client_id}-watchdog", daemon=True,
            )
            self._watchdog.start()
        return self

    def _announce(self, broker: BrokerClient) -> None:
        """Subscribe to our role topic BEFORE announcing (no race)."""
        broker.subscribe(enrollment.ROLE_TOPIC + str(self.client_id))
        from colearn_federated_learning_tpu.comm import keyexchange

        pubkey = keyexchange.encode_public(self._id_pub)
        enrollment.announce(broker, enrollment.DeviceInfo(
            device_id=str(self.client_id),
            host=self.host, port=self.port,
            num_examples=self.num_examples,
            dataset=self.config.data.dataset,
            pubkey=pubkey,
            mud=self._mud_profile,
        ))

    def _watch_broker(self, poll: float = 0.5) -> None:
        """Auto re-enrollment: when the broker connection dies (broker or
        coordinator host restarted), reconnect with backoff and re-announce
        — the retained enrollment record died with the old broker, so
        without this the device would be invisible to the next
        coordinator.  Each successful recovery is counted in
        ``comm.reenroll_total``."""
        from colearn_federated_learning_tpu import telemetry

        bh, bp = self._broker_addr
        backoff = poll
        while not self._watch_stop.wait(poll):
            broker = self._broker
            if broker is None or broker.alive():
                backoff = poll
                continue
            try:
                fresh = BrokerClient(bh, bp,
                                     timeout=protocol.CONNECT_TIMEOUT)
            except OSError:
                # Broker still down: back off (capped) and keep trying.
                if self._watch_stop.wait(backoff):
                    return
                backoff = min(5.0, backoff * 2.0)
                continue
            broker.close()
            self._broker = fresh
            if getattr(self, "_dh_mode", False):
                # The dedicated DH lookup connection died with the broker;
                # drop it so the next train request rebuilds it fresh.
                with self._dh_lock:
                    if self._dh_lookup is not None:
                        self._dh_lookup.close()
                        self._dh_lookup = None
            self._announce(fresh)
            telemetry.get_registry().counter("comm.reenroll_total").inc()
            backoff = poll

    def await_role(self, timeout: float = 30.0) -> str:
        if self._broker is None:
            raise RuntimeError("worker was started without a broker")
        self.role = enrollment.await_role(
            self._broker, str(self.client_id), timeout=timeout
        )
        return self.role

    def stop(self) -> None:
        # Stop the watchdog FIRST: our own broker close must not read as a
        # broker death and trigger a pointless re-enrollment.
        self._watch_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
        self._server.stop()
        if self._broker is not None:
            self._broker.close()
        if getattr(self, "_dh_mode", False):
            # Under the lock + a stopped flag: an in-flight train handler
            # must not recreate the lookup connection after we close it
            # (that would leak a socket + reader thread per restart).
            with self._dh_lock:
                self._dh_stopped = True
                if self._dh_lookup is not None:
                    self._dh_lookup.close()
                    self._dh_lookup = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------------
    def _handle(self, header: dict, tree: Any) -> tuple[dict, Any]:
        """Dispatch one request under a ``worker.<op>`` span.  When the
        request carries a trace context (protocol.attach_trace on the
        coordinator side), this span parents onto the coordinator's round
        span and every span finished while handling the request is
        returned in the reply meta for cross-process stitching."""
        op = header.get("op")
        ctx = protocol.extract_trace(header)
        attrs = {"client_id": self.client_id}
        if "round" in header:
            attrs["round"] = header["round"]
        with self.tracer.capture() as captured:
            with self.tracer.span(f"worker.{op}", parent=ctx, **attrs):
                out_header, out_tree = self._dispatch(op, header, tree)
        if ctx is not None and "meta" in out_header:
            out_header["meta"][protocol.TRACE_SPANS_KEY] = [
                s.to_dict() for s in captured
            ]
        return out_header, out_tree

    def _dispatch(self, op, header: dict, tree: Any) -> tuple[dict, Any]:
        if op == "train":
            return self._train(int(header.get("round", 0)), tree,
                               cohort=header.get("cohort"),
                               meta=header.get("meta"),
                               shares_in=header.get("shares_in"))
        if op == "share_setup":
            return self._share_setup(int(header.get("round", 0)),
                                     header.get("cohort", []))
        if op == "unmask":
            if "alive" in header:
                # Share-based recovery (privacy/dropout.py); the legacy
                # header shape below keeps the direct mask-sum semantics.
                return self._unmask_shares(int(header.get("round", 0)),
                                           header.get("dropped", []),
                                           header.get("alive", []))
            return self._unmask(int(header.get("round", 0)),
                                header.get("dropped", []),
                                header.get("cohort", []), tree)
        if op == "eval":
            return self._eval(tree)
        if op == "self_eval":
            return self._self_eval(tree)
        if op == "challenge":
            return self._challenge(header)
        if op == "info":
            return ({"meta": {"client_id": self.client_id,
                              "num_examples": self.num_examples,
                              "num_steps": self._num_steps}}, None)
        return ({"status": "error", "error": f"unknown op {op!r}"}, None)

    def _challenge(self, header: dict) -> tuple[dict, Any]:
        """Challenge-on-resume (coordinator.verify_resumed_devices):
        prove possession of the identity private key behind our announced
        pubkey by tagging the coordinator's nonce under the fresh
        ephemeral pairing it sent — sha256(DH(id_priv, eph_pub) ‖ nonce).
        A replayed or forged announcement cannot answer: the tag needs
        the private half the ledger's pubkey was derived from."""
        import hashlib

        from colearn_federated_learning_tpu.comm import keyexchange

        try:
            secret = keyexchange.shared_secret(
                self._id_priv,
                keyexchange.decode_public(str(header.get("pub", ""))))
            tag = hashlib.sha256(
                secret + bytes.fromhex(str(header.get("nonce", "")))
            ).hexdigest()
        except ValueError as e:
            return ({"status": "error", "error": f"bad challenge: {e}"},
                    None)
        return ({"meta": {"client_id": self.client_id, "tag": tag}}, None)

    def _partner_row(self, round_idx: int, cohort: list):
        """This client's secure-agg pairing partners for the round —
        derived from the shared experiment seed exactly like the engine
        (privacy/secure_agg.py), so no extra negotiation round is needed."""
        from colearn_federated_learning_tpu.privacy import secure_agg as sa

        cohort_ids = jnp.asarray(sorted(int(c) for c in cohort), jnp.int32)
        table = sa.partner_table(
            self._key, jnp.asarray([self.client_id], jnp.int32), cohort_ids,
            jnp.asarray(round_idx, jnp.int32),
            neighbors=self.config.fed.secure_agg_neighbors,
        )
        return table[0]

    def _dh_pair_keys(self, partner_ids, round_idx: int) -> tuple[Any, Any]:
        """(P, 2) uint32 pair-key rows + (P,) signs for ``partner_ids``,
        derived from Diffie-Hellman shared secrets — each row computable
        only by the two pair members, never by the coordinator.

        Peer public keys come from their RETAINED enrollment records,
        refetched once per ROUND (a restarted peer re-enrolls with a
        fresh ephemeral key; masking against its stale key would break
        pair cancellation and silently corrupt the aggregate).  The
        2048-bit modexp per pair is recomputed only when a peer's public
        key actually changed.  Runs on a DEDICATED broker connection —
        sharing the enrollment client's single message queue would race
        ``await_role`` and other concurrent train requests."""
        with self._dh_lock:
            keys, signs = [], []
            for p in np.asarray(partner_ids).tolist():
                p = int(p)
                if p == self.client_id:
                    keys.append(np.zeros(2, np.uint32))  # self-pair: sign 0
                    signs.append(0.0)
                    continue
                keys.append(self._peer_record(p, round_idx)[1])
                signs.append(1.0 if p > self.client_id else -1.0)
        return (jnp.asarray(np.stack(keys)),
                jnp.asarray(np.asarray(signs, np.float32)))

    def _peer_record(self, p: int, round_idx: int) -> tuple:  # colearn: holds(_dh_lock)
        """(pubkey_str, pair PRNG key uint32[2], raw DH secret bytes) for
        peer ``p``.  Caller holds ``_dh_lock``.  The secret bytes feed the
        share-transport keystream (privacy/dropout.py) so recovery shares
        relayed through the coordinator stay opaque to it."""
        from colearn_federated_learning_tpu.comm import keyexchange

        if self._dh_stopped:
            raise RuntimeError("worker is stopped")
        if self._dh_lookup is None:
            bh, bp = self._broker_addr
            # _dh_lock exists to serialize this dedicated connection (see
            # _pair_keys docstring); nothing latency-sensitive contends.
            self._dh_lookup = BrokerClient(  # colearn: noqa(CL019): _dh_lock serializes this dedicated connection by design; ctor bounded by CONNECT_TIMEOUT
                bh, bp, timeout=protocol.CONNECT_TIMEOUT)
        if self._peer_round != round_idx:
            self._peer_info_cache.clear()
            self._peer_round = round_idx
        info = enrollment.fetch_device_info(
            self._dh_lookup, str(p), cache=self._peer_info_cache
        )
        if not info.pubkey:
            raise RuntimeError(
                f"peer {p} enrolled without a DH public key; all "
                "cohort members must run secure_agg_key_exchange='dh'"
            )
        cached = self._peer_keys.get(p)
        if cached is None or cached[0] != info.pubkey:
            secret = keyexchange.shared_secret(
                self._dh_priv,
                keyexchange.decode_public(info.pubkey),
            )
            cached = (info.pubkey, np.asarray(
                keyexchange.pair_prng_key(secret, self.client_id, p)
            ), secret)
            self._peer_keys[p] = cached
        return cached

    def _recovery_set(self, round_idx: int, cohort: list) -> list:
        """Distinct non-self partner ids for the round — the Shamir
        shareholders.  Ring mode: the 2·neighbors ring peers; complete
        mode: everyone else in the cohort (or the GROUP under the
        hierarchical plane, which runs one federation per group)."""
        row = np.asarray(self._partner_row(round_idx, cohort)).tolist()
        return sorted({int(p) for p in row} - {self.client_id})

    def _share_setup(self, round_idx: int, cohort: list) -> tuple[dict, Any]:
        """Phase 1 of the dropout-tolerant secure round
        (privacy/dropout.py): mint this round's self-mask seed and
        Shamir-share it — together with the session DH secret — across the
        recovery set, one ciphertext per shareholder that only that peer
        can open.  The coordinator relays the ciphertexts on the train
        broadcast; a later ``unmask`` collects them back t-of-n."""
        if not self.config.fed.secure_agg:
            return ({"status": "error",
                     "error": "share_setup requires secure_agg"}, None)
        if not self._dh_mode:
            # shared_seed: the coordinator already knows every pair key
            # and recovers dropouts locally — nothing to distribute.
            return ({"meta": {"client_id": self.client_id, "shares": {},
                              "t": 0, "b_commit": ""}}, None)
        from colearn_federated_learning_tpu.privacy import dropout

        rs = self._recovery_set(round_idx, cohort)
        if not rs:
            # Solo cohort: no shareholders, hence no removable self-mask —
            # so none is applied either (see _train).
            self._store_round_secret(round_idx, None)
            return ({"meta": {"client_id": self.client_id, "shares": {},
                              "t": 0, "b_commit": ""}}, None)
        t = dropout.threshold_count(
            len(rs), self.config.fed.secure_agg_threshold)
        b = dropout.random_secret()
        xs = [p + 1 for p in rs]
        s_shares = dropout.split_secret(self._dh_priv, xs, t)
        b_shares = dropout.split_secret(b, xs, t)
        shares = {}
        with self._dh_lock:
            for p in rs:
                secret = self._peer_record(p, round_idx)[2]
                shares[str(p)] = dropout.encrypt_share(
                    secret, self.client_id, p, round_idx,
                    s_shares[p + 1], b_shares[p + 1],
                )
        self._store_round_secret(round_idx, b)
        return ({"meta": {"client_id": self.client_id, "shares": shares,
                          "t": t, "b_commit": dropout.commitment(b)}}, None)

    def _store_round_secret(self, round_idx: int, b) -> None:
        """Remember the round's self-mask seed; expire old rounds (the
        secrets and stashed shares are per-round, so a long-lived worker
        must not accumulate them forever)."""
        self._round_secrets[round_idx] = b
        cutoff = round_idx - 16
        if any(r < cutoff for r in self._round_secrets):
            self._round_secrets = {r: v for r, v in
                                   self._round_secrets.items() if r >= cutoff}
            self._incoming_shares = {k: v for k, v in
                                     self._incoming_shares.items()
                                     if k[0] >= cutoff}
            self._revealed = {k: v for k, v in self._revealed.items()
                              if k[0] >= cutoff}

    def _stash_shares(self, round_idx: int, shares_in: dict) -> None:
        """Decrypt and stash the round's incoming recovery shares (one
        ciphertext per origin, relayed opaquely by the coordinator)."""
        from colearn_federated_learning_tpu.privacy import dropout

        with self._dh_lock:
            for origin, blob in shares_in.items():
                o = int(origin)
                if o == self.client_id:
                    continue
                secret = self._peer_record(o, round_idx)[2]
                self._incoming_shares[(round_idx, o)] = dropout.decrypt_share(
                    secret, o, self.client_id, round_idx, blob)

    def _unmask_shares(self, round_idx: int, dropped: list,
                       alive: list) -> tuple[dict, Any]:
        """Share-based dropout recovery: reveal the SELF-MASK share for
        origins whose masked update folded and the SESSION-SECRET share
        for origins reported dead — never both for one (round, origin),
        enforced by a persistent ledger (revealing both would hand the
        coordinator a folded client's bare update)."""
        s_out: dict = {}
        b_out: dict = {}
        reply: dict = {"client_id": self.client_id,
                       "s_shares": s_out, "b_shares": b_out}
        for kind, ids, out in (("s", dropped, s_out), ("b", alive, b_out)):
            for o in ids:
                o = int(o)
                if o == self.client_id:
                    # Own session secret is NEVER revealed.  Own self-mask
                    # seed MAY be, once this round's update has folded —
                    # revealing b_u for an alive u is exactly what the
                    # share path reconstructs anyway, and it is the only
                    # recovery when every share-holder was pruned (n=2
                    # with the lone peer down).  Ledger still applies.
                    if kind == "b":
                        b = self._round_secrets.get(round_idx)
                        prior = self._revealed.get((round_idx, o))
                        if b is not None and prior in (None, "b"):
                            self._revealed[(round_idx, o)] = "b"
                            reply["b_self"] = format(b, "x")
                    continue
                stash = self._incoming_shares.get((round_idx, o))
                if stash is None:
                    continue
                prior = self._revealed.get((round_idx, o))
                if prior is not None and prior != kind:
                    continue      # exclusivity: refuse the second kind
                self._revealed[(round_idx, o)] = kind
                out[str(o)] = format(stash[0] if kind == "s" else stash[1],
                                     "x")
        return ({"meta": reply}, None)

    def _resolve_params(self, round_idx: int, meta: Optional[dict],
                        tree: Any) -> Any:
        """Materialize the round's full global params from a broadcast.

        Plain broadcasts (no downlink mode in ``meta``) pass through
        untouched — zero cost when compress_down is off.  Compressed
        broadcasts engage the :class:`downlink.WorkerParamCache`; ``None``
        means the cache cannot reconstruct (restart / skipped round) and
        the caller must answer with a resync request."""
        mode = meta.get(downlink.DOWN_KEY) if meta else None
        if mode is None and self._param_cache is None:
            return tree
        if self._param_cache is None:
            self._param_cache = downlink.WorkerParamCache()
        return self._param_cache.resolve(round_idx, meta or {}, tree)

    def _train(self, round_idx: int, global_params: Any,
               cohort=None, meta=None, shares_in=None) -> tuple[dict, Any]:
        with self.tracer.span("deserialize_params"):
            full = self._resolve_params(round_idx, meta, global_params)
            if full is None:
                # Explicit cache-miss reply: the coordinator re-sends full
                # params (comm.resync_total) instead of this device
                # training on garbage or silently dropping out.  The
                # feedback residual belongs to an update that never made
                # it into the fold — drop it with the stale base (and the
                # adaptive-topk trend, which tracked that residual).
                self._uplink_residual = None
                self._last_residual_norm = None
                return ({"status": "resync",
                         "error": f"client {self.client_id} has no cached "
                                  f"base for round {round_idx} delta"},
                        None)
            if self._lora:
                # Composite broadcast: frozen base + this cycle's factors
                # (compress_down is rejected under lora, so the tree is
                # always the plain decoded frame).
                args = (jax.tree.map(jnp.asarray, full["base"]),
                        jax.tree.map(jnp.asarray, full["factors"]))
            else:
                args = (jax.tree.map(jnp.asarray, full),)
        with self.tracer.span("local_train", steps=self._num_steps):
            result = self._update_fn(
                *args, self._x, self._y, self._count,
                prng.client_round_key(self._key, self.client_id, round_idx),
                jnp.asarray(self._num_steps, jnp.int32),
                strategies.lr_scale_for_round(self.config.fed, round_idx),
            )
            # The update is dispatched asynchronously; settle it here so
            # the span (and not the later serialization) carries the
            # compute time.
            jax.block_until_ready(result.delta)
        delta, weight = setup_lib.finalize_client_delta(
            self.config, result, self.client_id, round_idx
        )
        if self.config.fed.secure_agg:
            if not cohort:
                return ({"status": "error",
                         "error": "secure_agg train request lacks the "
                                  "round cohort"}, None)
            # Masked aggregation is a plain SUM: uniform weighting, like
            # the engine's secure path.
            from colearn_federated_learning_tpu.privacy import secure_agg as sa

            if self._dh_mode and shares_in:
                # Peers' recovery-share ciphertexts ride the train request;
                # stash them decrypted so a later unmask can answer t-of-n.
                self._stash_shares(round_idx, shares_in)
            with self.tracer.span("secure_mask", dh=self._dh_mode):
                delta_f32 = jax.tree.map(
                    lambda l: l.astype(jnp.float32), delta
                )
                partners = self._partner_row(round_idx, cohort)
                if self._dh_mode:
                    pair_keys, signs = self._dh_pair_keys(partners, round_idx)
                    delta = sa.mask_update_with_keys(
                        delta_f32, pair_keys, signs,
                        jnp.asarray(round_idx, jnp.int32),
                    )
                    b = self._round_secrets.get(round_idx)
                    if b is not None:
                        # Double-mask: the self-mask rides ONLY when this
                        # round's share_setup distributed its removal
                        # shares — an unremovable self-mask would poison
                        # the aggregate (and a raw train request without a
                        # share phase keeps the legacy single-mask wire).
                        from colearn_federated_learning_tpu.privacy import (
                            dropout,
                        )

                        delta = sa.mask_update_with_keys(
                            delta,
                            jnp.asarray(dropout.self_mask_key(b))[None, :],
                            jnp.ones(1, jnp.float32),
                            jnp.asarray(round_idx, jnp.int32),
                        )
                else:
                    delta = sa.mask_update(
                        delta_f32, self._key,
                        jnp.asarray(self.client_id, jnp.int32), partners,
                        jnp.asarray(round_idx, jnp.int32),
                    )
            weight = 1.0
        meta = {"round": round_idx, "weight": weight,
                "client_id": self.client_id,
                "num_examples": int(result.num_examples)}
        if not self.config.fed.secure_agg:
            # Per-client loss is exactly the statistic the masks hide;
            # ship it only on the unmasked plane.
            meta["mean_loss"] = float(result.mean_loss)
        from colearn_federated_learning_tpu import telemetry
        from colearn_federated_learning_tpu.fed import compression
        from colearn_federated_learning_tpu.utils import pytrees

        fed = self.config.fed
        feedback = (fed.compress_feedback and not fed.secure_agg
                    and fed.compress != "none")
        with self.tracer.span("compress_delta", codec=fed.compress):
            delta_np = jax.tree.map(np.asarray, delta)
            if feedback:
                wire, cmeta, self._uplink_residual = (
                    compression.feedback_compress(
                        delta_np, self._uplink_residual, fed.compress,
                        topk_fraction=self._topk_fraction))
                norm = float(
                    pytrees.tree_global_norm(self._uplink_residual))
                telemetry.get_registry().gauge(
                    "fed.uplink_residual_norm").set(norm)
                self._adapt_topk(norm)
            else:
                wire, cmeta = compression.compress_delta(
                    delta_np, fed.compress,
                    topk_fraction=fed.topk_fraction)
        meta.update(cmeta)
        return ({"meta": meta}, wire)

    def _adapt_topk(self, norm: float) -> None:
        """Adaptive per-round topk density (fed.topk_adaptive): when the
        error-feedback residual norm GROWS round-over-round the codec is
        dropping signal faster than feedback re-injects it — widen the
        frame (×1.25); when it shrinks, the density is more than the
        delta needs — tighten (×0.9, gentler so density decays only under
        sustained slack).  Clipped to the configured
        [topk_min_fraction, topk_max_fraction] band; the effective
        fraction is exported on ``fed.topk_fraction_effective``."""
        if not getattr(self.config.fed, "topk_adaptive", False):
            return
        from colearn_federated_learning_tpu import telemetry

        fed = self.config.fed
        prev, self._last_residual_norm = self._last_residual_norm, norm
        if prev is not None:
            if norm > prev:
                self._topk_fraction *= 1.25
            elif norm < prev:
                self._topk_fraction *= 0.9
        self._topk_fraction = min(
            float(fed.topk_max_fraction),
            max(float(fed.topk_min_fraction), self._topk_fraction))
        telemetry.get_registry().gauge(
            "fed.topk_fraction_effective").set(self._topk_fraction)

    def _unmask(self, round_idx: int, dropped: list, cohort: list,
                _tree: Any) -> tuple[dict, Any]:
        """Dropout recovery (Bonawitz pattern, honest-but-curious): return
        the SUM of this client's pairwise masks shared with the dropped
        peers it had paired with, exactly as it ADDED them — the
        coordinator subtracts these to cancel the orphaned mask halves."""
        from colearn_federated_learning_tpu.privacy import secure_agg as sa

        partners = np.asarray(self._partner_row(round_idx, cohort))
        mine = jnp.asarray(
            [int(d) for d in dropped if int(d) in set(partners.tolist())],
            jnp.int32,
        )
        template = jax.tree.map(
            lambda l: jnp.zeros(l.shape, jnp.float32), self._template_params()
        )
        if mine.size == 0:
            # No shared pairs with the dropped peers: a payload-free reply
            # (shipping a model-sized zero tree would cost cohort x model
            # bytes per dropout in ring mode).
            return ({"meta": {"client_id": self.client_id,
                              "n_dropped_pairs": 0}}, None)
        if self._dh_mode:
            pair_keys, signs = self._dh_pair_keys(mine, round_idx)
            mask = sa.pairwise_mask_with_keys(
                template, pair_keys, signs,
                jnp.asarray(round_idx, jnp.int32),
            )
        else:
            mask = sa.pairwise_mask(
                template, self._key,
                jnp.asarray(self.client_id, jnp.int32), mine,
                jnp.asarray(round_idx, jnp.int32),
            )
        return ({"meta": {"client_id": self.client_id,
                          "n_dropped_pairs": int(mine.size)}},
                jax.tree.map(np.asarray, mask))

    def _template_params(self):
        """Shape template for the wire payload this worker ships — the
        factor tree under lora (masks/recovery frames must match what was
        masked), the full param tree otherwise."""
        if not hasattr(self, "_param_template"):
            params = setup_lib.init_global_params(self.config)
            if self._lora:
                from colearn_federated_learning_tpu.fed import lora

                params = lora.init_factors(
                    params, self.config.fed.lora_rank,
                    model_name=self.config.model.name)
            self._param_template = params
        return self._param_template

    def _self_eval(self, global_params: Any) -> tuple[dict, Any]:
        """Score the global model on THIS device's own shard — the
        federated-native complement of the evaluator role (the engine's
        ``evaluate_per_client``): how well the global model fits each
        client's local distribution under non-IID partitions."""
        if self.config.fed.secure_agg:
            # Per-client statistics are exactly what the masks hide; the
            # device refuses regardless of who asks.
            return ({"status": "error",
                     "error": "self_eval is disabled under secure_agg"},
                    None)
        from colearn_federated_learning_tpu.fed.evaluation import make_eval_fn

        if not hasattr(self, "_self_eval_fn"):
            n = self.num_examples
            self._self_eval_fn = make_eval_fn(
                self._model.apply,
                np.asarray(self._x[:n]), np.asarray(self._y[:n]),
                batch=max(self.config.fed.batch_size, 64),
            )
        params = jax.tree.map(jnp.asarray, global_params)
        loss, acc = self._self_eval_fn(params)
        return ({"meta": {"client_id": self.client_id,
                          "num_examples": self.num_examples,
                          "self_loss": float(loss),
                          "self_acc": float(acc)}}, None)

    def _eval(self, global_params: Any) -> tuple[dict, Any]:
        if self._eval_fn is None:
            from colearn_federated_learning_tpu.fed.evaluation import make_eval_fn

            self._eval_fn = make_eval_fn(
                self._model.apply, self._dataset.x_test, self._dataset.y_test,
                batch=max(self.config.fed.batch_size, 64),
            )
        params = jax.tree.map(jnp.asarray, global_params)
        loss, acc = self._eval_fn(params)
        return ({"meta": {"eval_loss": float(loss),
                          "eval_acc": float(acc)}}, None)


def run_worker_forever(config: ExperimentConfig, client_id: int,
                       broker_host: str, broker_port: int,
                       mud_profile: Optional[str] = None) -> None:
    """CLI entry: serve until the process is killed.  The enrollment
    window is ``config.run.worker_enroll_timeout``; expiry raises
    :class:`enrollment.EnrollmentTimeout` instead of hanging forever."""
    worker = DeviceWorker(config, client_id, broker_host, broker_port,
                          mud_profile=mud_profile).start()
    try:
        worker.await_role(timeout=config.run.worker_enroll_timeout)
        threading.Event().wait()      # serve forever
    finally:
        worker.stop()
