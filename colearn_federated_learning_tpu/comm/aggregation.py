"""Weighted folding of client updates — shared by both socket coordinators.

The synchronous round loop (comm/coordinator.py) and the buffered
asynchronous aggregator (comm/async_coordinator.py) accumulate the same
thing: decompressed client deltas scaled by their aggregation weight, a
running weight total, and a weighted loss.  One helper keeps the two
planes' aggregation math identical (decompression, weighting, the guarded
zero-weight mean) — the host-side mirror of the engine's in-XLA
``tree_weighted_sum`` / ``_finish_round`` pair.
"""

from __future__ import annotations

import math
import time
from typing import Any, Optional, Sequence

import jax
import numpy as np

from colearn_federated_learning_tpu.utils import pytrees


class _SparseStage:
    """One topk contribution staged sparse: per leaf (flatten order), a
    list of ``(flat_idx, scaled_values, target_shape)`` triples — one per
    shard under a ServerPlacement, exactly one otherwise.  Total staged
    memory is O(k), never O(model)."""

    __slots__ = ("leaves",)

    def __init__(self, leaves: list):
        self.leaves = leaves


class _RawSparseStage:
    """One topk contribution staged for the DEVICE fold: per slot (leaf
    or leaf-shard, flatten order), ``(flat_idx int64, raw_values,
    dequant_scale)`` — topk8 values stay int8 so the fused kernel decodes
    them in-kernel; plain topk values stay float32 with scale 1.0.  The
    aggregation weight is NOT pre-applied (the kernel multiplies it in
    host order: ``(value * scale) * weight``)."""

    __slots__ = ("slots", "vals_dtype")

    def __init__(self, slots: list, vals_dtype: Any):
        self.slots = slots
        self.vals_dtype = vals_dtype


def _own_leaf(leaf: Any) -> np.ndarray:
    """Staging-time ownership normalization: a writable, C-contiguous
    array the fold can mutate in place.  Copies AT MOST once per staged
    leaf — the hoisted replacement for the old per-scatter defensive copy
    in ``_scatter_fold``."""
    a = np.asarray(leaf)
    if not (a.flags.writeable and a.flags.c_contiguous):
        a = np.array(a)
    return a


def _merge_dense(acc: Any, contrib: Any) -> Any:
    """Elementwise host add for the dense fold, in place when the
    accumulator permits (staged leaves are owned and single-use, so
    mutating them is safe) — bitwise identical to the jnp ``tree_add`` it
    replaces, but the result stays OWNED writable numpy, so a sparse
    scatter landing on it later never has to copy."""
    def add(a, c):
        a = np.asarray(a)
        if a.flags.writeable and a.dtype == np.result_type(a, c):
            return np.add(a, c, out=a)
        return np.add(a, c)
    return jax.tree.map(add, acc, contrib)


class UpdateFolder:
    """Accumulate weighted client deltas; ``mean()`` is None-safe."""

    def __init__(self, shapes: Any):
        self.shapes = shapes            # params-shaped numpy pytree
        self.wsum: Optional[Any] = None
        self.total_w = 0.0
        self.loss_sum = 0.0
        self.count = 0

    def add(self, meta: dict, delta: Any,
            weight: Optional[float] = None) -> float:
        """Fold one update.  ``weight`` overrides the worker-reported
        ``meta["weight"]`` (the async plane multiplies in its staleness
        discount).  Returns the weight actually applied."""
        from colearn_federated_learning_tpu.fed import compression

        delta = compression.decompress_delta(delta, meta, shapes=self.shapes)
        w = float(meta.get("weight", 1.0)) if weight is None else float(weight)
        contrib = pytrees.tree_scale(jax.tree.map(np.asarray, delta), w)
        self.wsum = (
            contrib if self.wsum is None
            else pytrees.tree_add(self.wsum, contrib)
        )
        self.total_w += w
        self.loss_sum += float(meta.get("mean_loss", 0.0)) * w
        self.count += 1
        return w

    def mean(self) -> tuple[Optional[Any], float, float]:
        """(mean_delta | None, total_weight, weighted_mean_loss).  A fold
        with zero total weight yields (None, 0, nan) — callers skip the
        server step rather than divide by zero."""
        if self.total_w <= 0.0:
            return None, 0.0, math.nan
        return (
            pytrees.tree_scale(self.wsum, 1.0 / self.total_w),
            self.total_w,
            self.loss_sum / self.total_w,
        )


class StreamingFolder(UpdateFolder):
    """UpdateFolder whose heavy per-update work happens at ARRIVAL time.

    The streaming fan-out calls :meth:`add` from the collector as each
    reply lands, so decompression + numpy conversion + weight scaling (the
    dominant host cost per update) overlap the stragglers still training.
    The cheap final summation is deferred to :meth:`finalize` and runs in
    ``order`` (the round's cohort order) — NOT arrival order — so the fold
    is bitwise identical to the barrier fold it replaces and exactly
    invariant to reply timing.  Float sums stay run-to-run deterministic;
    no reordering tolerance is needed (tests assert exact equality).

    ``fold_s`` accumulates time spent inside ``add`` — the work the
    overlap hides — surfaced as the round's ``phase_fold_overlap_s``.

    With ``placement`` (a :class:`parallel.partition.ServerPlacement`, the
    PR 9 sharded server) every staged contribution is immediately SLICED
    into its per-shard layout — the symmetric scatter of the uplink decode
    — so the fold accumulates shard-wise and :meth:`mean` assembles a
    sharded ``jax.Array`` tree where each device receives only its own
    shard bytes (no replicated device intermediate).  Per element the sum
    sequence is unchanged (same contributions, same cohort order), so the
    sharded fold is BITWISE identical to the replicated one.

    TOPK contributions never densify (the uplink fast path): ``add``
    stages the wire's ``(indices, values)`` scaled by the aggregation
    weight — O(k) host work per update instead of O(model) — and
    ``finalize`` scatter-adds them into the dense accumulator in cohort
    order, bitwise identical to the densify-then-sum fold it replaces
    (adding exact zeros is an IEEE no-op).  Under a placement the staged
    indices are partitioned per shard with offset-adjusted coordinates
    (``ServerPlacement.partition_flat_indices``), so the tp>1 sparse fold
    stays bitwise equal to the replicated one.  ``densify_avoided``
    counts contributions folded sparse (mirrored to the
    ``comm.uplink_densify_avoided_total`` counter).

    ``slices`` (the aggregator-tree reference layout) partitions the
    cohort order into contiguous blocks: :meth:`finalize` folds each
    block sequentially into its own partial (weighted sum, total weight
    AND weighted loss all accumulate block-locally from zero), then
    combines the block partials sequentially in block order.  That is
    float addition REGROUPED at the block boundaries — exactly the sum
    the distributed aggregator tier computes when each aggregator folds
    its slice and the root folds the N partials — so a flat folder built
    with the tree's slice layout is the BITWISE oracle for the tree fold
    (parity tests pin it, dense and topk, replicated and sharded).
    ``slices=None`` (every existing call site) keeps the single-pass
    fold byte-identical to before; a single all-cohort slice is also
    bitwise identical to ``None`` (``0.0 + x == x`` for the positive
    weights and the first block's partial is adopted, never re-added).
    Staged ids not covered by any slice (stragglers admitted past the
    layout) fold as one trailing block.
    """

    def __init__(self, shapes: Any, order: Optional[Sequence[str]] = None,
                 placement: Optional[Any] = None,
                 slices: Optional[Sequence[Sequence[str]]] = None,
                 device_fold: bool = False):
        super().__init__(shapes)
        self._order = list(order) if order is not None else None
        self._staged: dict[str, tuple[float, Any, float]] = {}
        self._placement = placement
        self._slices = ([list(s) for s in slices]
                        if slices is not None else None)
        self.fold_s = 0.0
        self.folded_ids: list[str] = []
        self.densify_avoided = 0
        self._finalized = False
        # Device-resident fold (--fold-device, ops/fold_kernel.py): topk
        # contributions stage RAW (int8 + scale, weight unapplied) and
        # each finalize block folds through the fused batched kernel —
        # bitwise-pinned against this host path, which stays the parity
        # oracle.  The kernel is fetched lazily (shape-fingerprint cache:
        # one compile per MODEL, not per folder/round) and the batch cap
        # is an internal knob the fold bench uses to price batch=1 vs K.
        self._device_fold = bool(device_fold)
        self._kernel = None
        self._slot_meta: Optional[list] = None
        self._fold_batch_max: Optional[int] = None

    def add(self, meta: dict, delta: Any,  # colearn: hot
            weight: Optional[float] = None) -> float:
        from colearn_federated_learning_tpu import telemetry
        from colearn_federated_learning_tpu.fed import compression

        if self._finalized:
            raise RuntimeError("StreamingFolder already finalized")
        t0 = time.perf_counter()
        w = float(meta.get("weight", 1.0)) if weight is None else float(weight)
        if meta.get("compress") in compression.TOPK_SCHEMES:
            # Sparse-native staging: the wire's (indices, values) stay
            # sparse — O(k) copy + scale here, cohort-order scatter-add at
            # finalize (topk8 values dequantize inside topk_leaf_arrays;
            # the device fold defers even the dequant into the kernel).
            # No full-shape tensor is materialized per update.
            contrib = (self._stage_topk_raw(delta, w) if self._device_fold
                       else self._stage_topk(delta, w))
            self.densify_avoided += 1
            telemetry.get_registry().counter(
                "comm.uplink_densify_avoided_total").inc()
        else:
            # int8 dequantize is inherently dense (every entry carries
            # signal); "none" already arrives dense.
            delta = compression.decompress_delta(  # colearn: noqa(CL013): int8/none payloads are inherently dense
                delta, meta, shapes=self.shapes)
            # Per-leaf host scale: wire deltas are numpy straight off the
            # decode, and the multiply hands the fold an OWNED, writable,
            # C-contiguous contribution — the in-place scatter/merge
            # downstream never needs a defensive copy.
            leaves, treedef = jax.tree.flatten(delta)
            contrib = jax.tree.unflatten(
                treedef, [np.asarray(leaf) * w for leaf in leaves])
            if self._placement is not None:
                # Shard-wise staging: each leaf becomes the tuple of its
                # per-shard slices (uplink decode scattered symmetrically).
                contrib = self._placement.slice_tree(contrib)
        cid = str(meta.get("client_id", len(self._staged)))
        self._staged[cid] = (w, contrib,
                             float(meta.get("mean_loss", 0.0)) * w)
        self.count += 1
        self.fold_s += time.perf_counter() - t0
        return w

    def _stage_topk(self, wire_tree: Any, w: float) -> _SparseStage:
        """Stage one topk wire tree as scaled (indices, values) — the
        O(k) replacement for decompress + tree_scale (+ slice_tree under
        a placement).  Scaling values before the scatter is bitwise
        identical to scaling after densify: the elementwise f32 multiply
        commutes with slicing, and the dense path's ``0.0 * w`` zeros are
        exactly the ``np.zeros`` the scatter targets at finalize."""
        from colearn_federated_learning_tpu.fed import compression

        treedef = jax.tree.structure(self.shapes)
        refs = jax.tree.leaves(self.shapes)
        nodes = treedef.flatten_up_to(wire_tree)
        sw = np.float32(w)
        leaves = []
        for pos, (node, ref) in enumerate(zip(nodes, refs)):
            idx, vals, _ = compression.topk_leaf_arrays(node)
            vals = vals * sw
            if self._placement is not None:
                leaves.append(
                    self._placement.partition_flat_indices(pos, idx, vals))
            else:
                leaves.append([(idx, vals, tuple(np.shape(ref)))])
        return _SparseStage(leaves)

    def _stage_topk_raw(self, wire_tree: Any, w: float) -> _RawSparseStage:
        """Stage one topk wire tree RAW for the device fold: indices as
        int64, values undecoded (int8 for topk8), per-leaf dequant scale
        riding along — the kernel applies ``(value * scale) * weight``
        itself, in exactly the host path's multiply order.  O(k) host
        work, no dequant, no scale pass."""
        from colearn_federated_learning_tpu.fed import compression

        treedef = jax.tree.structure(self.shapes)
        nodes = treedef.flatten_up_to(wire_tree)
        slots: list = []
        vdt = np.dtype(np.float32)
        for pos, node in enumerate(nodes):
            idx, vals, scale, _ = compression.topk_leaf_raw(node)
            idx = np.ascontiguousarray(idx, np.int64)
            vdt = vals.dtype
            if self._placement is not None:
                # Offset-adjusted per-shard partitioning preserves the
                # raw value dtype (boolean masking never casts).
                for li, lv, _shape in self._placement.partition_flat_indices(
                        pos, idx, vals):
                    slots.append((li, lv, scale))
            else:
                slots.append((idx, vals, scale))
        return _RawSparseStage(slots, vdt)

    def add_partial(self, key: str, total_w: float, tree: Any,
                    loss_sum: float, count: int = 1) -> None:
        """Stage one PRE-FOLDED partial sum (an aggregator's slice fold):
        ``tree`` is the slice's weighted-sum tree (dense host leaves, or
        ``None`` for a slice that folded nothing), ``total_w``/``loss_sum``
        the slice's accumulated weight and weighted loss.  :meth:`finalize`
        combines partials sequentially in ``order`` — the cross-block sum
        of the slice-blocked flat fold, so root-side combination is
        bitwise identical to a flat folder built with the same
        ``slices``."""
        if self._finalized:
            raise RuntimeError("StreamingFolder already finalized")
        t0 = time.perf_counter()
        contrib = None
        if tree is not None:
            # Ownership is normalized HERE, at staging (at most one copy
            # per leaf, and only for read-only/non-contiguous inputs) —
            # the fold's in-place scatter/merge relies on it.
            leaves, treedef = jax.tree.flatten(tree)
            contrib = jax.tree.unflatten(treedef,
                                         [_own_leaf(l) for l in leaves])
            if self._placement is not None:
                # Slicing commutes elementwise with the adds below, so the
                # sharded combine stays bitwise equal to the replicated one.
                contrib = self._placement.slice_tree(contrib)
        self._staged[str(key)] = (float(total_w), contrib, float(loss_sum))
        self.count += int(count)
        self.fold_s += time.perf_counter() - t0

    def has(self, key: str) -> bool:
        """True while ``key`` is staged and not yet finalized."""
        return str(key) in self._staged

    def discard(self, key: str) -> bool:
        """Drop one staged contribution before finalize (dedup/re-home:
        the buffered aggregator discards the stale copy before re-staging
        a contribution under the same dedup key, keeping ``count`` and the
        fold itself single-copy).  Returns True when something was
        dropped; a finalized folder refuses (the sum already includes the
        contribution)."""
        if self._finalized:
            raise RuntimeError("StreamingFolder already finalized")
        if self._staged.pop(str(key), None) is None:
            return False
        self.count -= 1
        return True

    def _scatter_fold(self, acc: Any, stage: _SparseStage) -> Any:
        """Fold one sparse-staged contribution into the accumulator.

        First contribution: densify by ASSIGNMENT into fresh zeros —
        byte-identical to the dense path's decompress-then-scale leaf.
        Later contributions: in-place scatter-add at the staged indices.
        Untouched positions keep their accumulator bits; the dense path
        adds an exact ``+0.0`` there, an IEEE no-op except that it would
        normalize a ``-0.0`` accumulator entry to ``+0.0`` — a corner the
        magnitude-topk codec never ships and the parity tests pin.

        Accumulation stays in OWNED, writable, C-contiguous host numpy by
        STAGING-TIME invariant: dense contributions are owned by their
        scale multiply, partials by ``_own_leaf``, sharded slices by
        ``slice_tree``'s ``ascontiguousarray``, and the dense merge
        (``_merge_dense``) writes through numpy — so the in-place scatter
        below is always safe.  The old per-scatter writability check/copy
        is gone: normalization happens at most once per leaf, at staging,
        never per fold step."""
        treedef = jax.tree.structure(self.shapes)
        if acc is None:
            out = []
            for shards in stage.leaves:
                parts = []
                for idx, vals, shape in shards:
                    flat = np.zeros(
                        int(np.prod(shape, dtype=np.int64)), np.float32)
                    flat[idx] = vals
                    parts.append(flat.reshape(shape))
                out.append(tuple(parts) if self._placement is not None
                           else parts[0])
            return jax.tree.unflatten(treedef, out)
        acc_leaves = treedef.flatten_up_to(acc)
        new_leaves = []
        for acc, shards in zip(acc_leaves, stage.leaves):
            sharded = isinstance(acc, tuple)
            targets = list(acc) if sharded else [acc]
            for j, (arr, (idx, vals, _)) in enumerate(zip(targets, shards)):
                # reshape(-1) of a C-contiguous array is a VIEW — the +=
                # mutates the accumulator (and handles 0-d leaves, which
                # reject direct fancy indexing).
                arr.reshape(-1)[idx] += vals
                targets[j] = arr
            new_leaves.append(tuple(targets) if sharded else targets[0])
        return jax.tree.unflatten(treedef, new_leaves)

    def _fold_block(self, ids: Sequence[str]) -> tuple[Any, float, float]:
        """Fold one block of staged ids sequentially from scratch —
        weighted sum, total weight and weighted loss all accumulate
        block-locally (exactly what a slice aggregator computes).  The
        dense merge runs through ``_merge_dense`` (host numpy, in place):
        bit-identical to the jnp ``tree_add`` it replaces, but the
        accumulator stays writable so an interleaved sparse scatter never
        copies."""
        acc, tw, ls = None, 0.0, 0.0
        for cid in ids:
            w, contrib, loss_w = self._staged[cid]
            if isinstance(contrib, _SparseStage):
                acc = self._scatter_fold(acc, contrib)
            elif contrib is not None:
                acc = (contrib if acc is None
                       else _merge_dense(acc, contrib))
            tw += w
            ls += loss_w
        return acc, tw, ls

    def _slot_layout(self) -> list:
        """Per leaf (flatten order): the list of slot shapes the device
        fold accumulates into — one per distinct shard under a placement
        (``slice_tree``'s slice order), exactly one otherwise."""
        if self._slot_meta is None:
            refs = jax.tree.leaves(self.shapes)
            if self._placement is None:
                self._slot_meta = [[tuple(np.shape(r))] for r in refs]
            else:
                no_idx = np.zeros(0, np.int64)
                no_val = np.zeros(0, np.float32)
                self._slot_meta = [
                    [tuple(shape) for _, _, shape in
                     self._placement.partition_flat_indices(
                         pos, no_idx, no_val)]
                    for pos in range(len(refs))
                ]
        return self._slot_meta

    def _dense_slots(self, contrib: Any) -> list:
        """One staged dense/partial tree as the kernel's flat slot list
        (views, not copies — staged leaves are C-contiguous)."""
        slots = []
        for leaf in jax.tree.structure(self.shapes).flatten_up_to(contrib):
            for part in (leaf if isinstance(leaf, tuple) else (leaf,)):
                slots.append(np.asarray(part).reshape(-1))
        return slots

    def _fold_block_device(self, ids: Sequence[str]) -> tuple:  # colearn: hot
        """Device-resident block fold: batch the staged contributions
        through the fused kernel (ops/fold_kernel.py) — sparse runs fold
        as ONE batched scatter dispatch (in-kernel dequant + weighting),
        dense runs as one batched add — and convert to host exactly once
        at block end.  Runs split only at sparse/dense (or value-dtype)
        boundaries, so the kernel's scan order is the cohort order and
        the result is bitwise identical to :meth:`_fold_block`, the
        parity oracle."""
        from colearn_federated_learning_tpu import telemetry
        from colearn_federated_learning_tpu.ops import fold_kernel

        kernel = self._kernel
        if kernel is None:
            sizes = [int(np.prod(shape, dtype=np.int64)) if shape else 1
                     for group in self._slot_layout() for shape in group]
            kernel = self._kernel = fold_kernel.get_kernel(sizes)
        acc = None
        tw, ls, folded = 0.0, 0.0, 0
        cap = self._fold_batch_max or len(ids) or 1
        sparse_run: list = []
        dense_run: list = []
        run_dtype = None

        def flush_sparse():
            nonlocal acc
            while sparse_run:
                acc = kernel.fold_sparse(acc, sparse_run[:cap])
                del sparse_run[:cap]

        def flush_dense():
            nonlocal acc
            while dense_run:
                acc = kernel.fold_dense(acc, dense_run[:cap])
                del dense_run[:cap]

        for cid in ids:
            w, contrib, loss_w = self._staged[cid]
            tw += w
            ls += loss_w
            if isinstance(contrib, _RawSparseStage):
                if dense_run:
                    flush_dense()
                if sparse_run and run_dtype != contrib.vals_dtype:
                    flush_sparse()
                run_dtype = contrib.vals_dtype
                sparse_run.append((np.float32(w), contrib.slots))
                folded += 1
            elif contrib is not None:
                if sparse_run:
                    flush_sparse()
                dense_run.append(self._dense_slots(contrib))
                folded += 1
        flush_sparse()
        flush_dense()
        if folded:
            telemetry.get_registry().counter(
                "comm.fold_device_total").inc(folded)
        if acc is None:
            return None, tw, ls
        leaves = kernel.to_host(acc)
        it = iter(leaves)
        out = []
        for group in self._slot_layout():
            parts = [next(it).reshape(shape) for shape in group]
            out.append(tuple(parts) if self._placement is not None
                       else parts[0])
        tree = jax.tree.unflatten(jax.tree.structure(self.shapes), out)
        return tree, tw, ls

    def finalize(self) -> None:
        """Sum the staged contributions in cohort order (idempotent).
        Must run before :meth:`mean` or any direct ``wsum`` consumer
        (secure-agg unmasking mutates ``wsum`` after this).  With
        ``slices`` the sum is regrouped at the block boundaries — see the
        class docstring; without, one block reproduces the historical
        single-pass fold bitwise."""
        if self._finalized:
            return
        self._finalized = True
        order = (self._order if self._order is not None
                 else sorted(self._staged))
        ids = [cid for cid in order if cid in self._staged]
        ids += [cid for cid in self._staged if cid not in ids]
        if self._slices is None:
            blocks = [ids]
        else:
            covered: set[str] = set()
            blocks = []
            for sl in self._slices:
                covered.update(str(c) for c in sl)
                blk = [str(c) for c in sl if str(c) in self._staged]
                if blk:
                    blocks.append(blk)
            stragglers = [cid for cid in ids if cid not in covered]
            if stragglers:
                blocks.append(stragglers)
            ids = [cid for blk in blocks for cid in blk]
        for blk in blocks:
            acc, tw, ls = (self._fold_block_device(blk) if self._device_fold
                           else self._fold_block(blk))
            if acc is not None:
                self.wsum = (acc if self.wsum is None
                             else _merge_dense(self.wsum, acc))
            self.total_w += tw
            self.loss_sum += ls
        self.folded_ids = ids
        self._staged.clear()

    def apply_correction(self, tree: Any) -> None:
        """Subtract a correction term from the finalized weighted sum —
        the secure-agg recovery hook: reconstructed self-masks and orphaned
        pair-mask halves are removed as ONE final term, never by
        densifying and re-summing the folded updates."""
        if not self._finalized:
            raise RuntimeError(
                "apply_correction requires a finalized fold (the "
                "correction is defined relative to the completed sum)"
            )
        if self.wsum is None:
            return
        if self._placement is not None:
            # Same per-shard layout as the staged contributions; the
            # subtraction runs slice-wise, elementwise-identical to the
            # full-leaf subtraction.
            tree = self._placement.slice_tree(tree)
        self.wsum = pytrees.tree_sub(self.wsum, tree)

    def mean(self) -> tuple[Optional[Any], float, float]:
        self.finalize()
        mean_delta, total_w, mean_loss = super().mean()
        if mean_delta is not None and self._placement is not None:
            # Per-shard slices → a sharded jax.Array tree: every device
            # receives exactly its own shard bytes, never the full leaf.
            mean_delta = self._placement.assemble(mean_delta)
        return mean_delta, total_w, mean_loss
