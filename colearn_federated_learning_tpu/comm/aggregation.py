"""Weighted folding of client updates — shared by both socket coordinators.

The synchronous round loop (comm/coordinator.py) and the buffered
asynchronous aggregator (comm/async_coordinator.py) accumulate the same
thing: decompressed client deltas scaled by their aggregation weight, a
running weight total, and a weighted loss.  One helper keeps the two
planes' aggregation math identical (decompression, weighting, the guarded
zero-weight mean) — the host-side mirror of the engine's in-XLA
``tree_weighted_sum`` / ``_finish_round`` pair.
"""

from __future__ import annotations

import math
import time
from typing import Any, Optional, Sequence

import jax
import numpy as np

from colearn_federated_learning_tpu.utils import pytrees


class UpdateFolder:
    """Accumulate weighted client deltas; ``mean()`` is None-safe."""

    def __init__(self, shapes: Any):
        self.shapes = shapes            # params-shaped numpy pytree
        self.wsum: Optional[Any] = None
        self.total_w = 0.0
        self.loss_sum = 0.0
        self.count = 0

    def add(self, meta: dict, delta: Any,
            weight: Optional[float] = None) -> float:
        """Fold one update.  ``weight`` overrides the worker-reported
        ``meta["weight"]`` (the async plane multiplies in its staleness
        discount).  Returns the weight actually applied."""
        from colearn_federated_learning_tpu.fed import compression

        delta = compression.decompress_delta(delta, meta, shapes=self.shapes)
        w = float(meta.get("weight", 1.0)) if weight is None else float(weight)
        contrib = pytrees.tree_scale(jax.tree.map(np.asarray, delta), w)
        self.wsum = (
            contrib if self.wsum is None
            else pytrees.tree_add(self.wsum, contrib)
        )
        self.total_w += w
        self.loss_sum += float(meta.get("mean_loss", 0.0)) * w
        self.count += 1
        return w

    def mean(self) -> tuple[Optional[Any], float, float]:
        """(mean_delta | None, total_weight, weighted_mean_loss).  A fold
        with zero total weight yields (None, 0, nan) — callers skip the
        server step rather than divide by zero."""
        if self.total_w <= 0.0:
            return None, 0.0, math.nan
        return (
            pytrees.tree_scale(self.wsum, 1.0 / self.total_w),
            self.total_w,
            self.loss_sum / self.total_w,
        )


class StreamingFolder(UpdateFolder):
    """UpdateFolder whose heavy per-update work happens at ARRIVAL time.

    The streaming fan-out calls :meth:`add` from the collector as each
    reply lands, so decompression + numpy conversion + weight scaling (the
    dominant host cost per update) overlap the stragglers still training.
    The cheap final summation is deferred to :meth:`finalize` and runs in
    ``order`` (the round's cohort order) — NOT arrival order — so the fold
    is bitwise identical to the barrier fold it replaces and exactly
    invariant to reply timing.  Float sums stay run-to-run deterministic;
    no reordering tolerance is needed (tests assert exact equality).

    ``fold_s`` accumulates time spent inside ``add`` — the work the
    overlap hides — surfaced as the round's ``phase_fold_overlap_s``.

    With ``placement`` (a :class:`parallel.partition.ServerPlacement`, the
    PR 9 sharded server) every staged contribution is immediately SLICED
    into its per-shard layout — the symmetric scatter of the uplink decode
    — so the fold accumulates shard-wise and :meth:`mean` assembles a
    sharded ``jax.Array`` tree where each device receives only its own
    shard bytes (no replicated device intermediate).  Per element the sum
    sequence is unchanged (same contributions, same cohort order), so the
    sharded fold is BITWISE identical to the replicated one.
    """

    def __init__(self, shapes: Any, order: Optional[Sequence[str]] = None,
                 placement: Optional[Any] = None):
        super().__init__(shapes)
        self._order = list(order) if order is not None else None
        self._staged: dict[str, tuple[float, Any, float]] = {}
        self._placement = placement
        self.fold_s = 0.0
        self.folded_ids: list[str] = []
        self._finalized = False

    def add(self, meta: dict, delta: Any,  # colearn: hot
            weight: Optional[float] = None) -> float:
        from colearn_federated_learning_tpu.fed import compression

        if self._finalized:
            raise RuntimeError("StreamingFolder already finalized")
        t0 = time.perf_counter()
        delta = compression.decompress_delta(delta, meta, shapes=self.shapes)
        w = float(meta.get("weight", 1.0)) if weight is None else float(weight)
        # Wire deltas are host numpy straight off the decode — the asarray
        # normalizes dtypes/views, it cannot touch a device.
        contrib = pytrees.tree_scale(
            jax.tree.map(np.asarray, delta), w)  # colearn: noqa(CL012)
        if self._placement is not None:
            # Shard-wise staging: each leaf becomes the tuple of its
            # per-shard slices (uplink decode scattered symmetrically).
            contrib = self._placement.slice_tree(contrib)
        cid = str(meta.get("client_id", len(self._staged)))
        self._staged[cid] = (w, contrib,
                             float(meta.get("mean_loss", 0.0)) * w)
        self.count += 1
        self.fold_s += time.perf_counter() - t0
        return w

    def finalize(self) -> None:
        """Sum the staged contributions in cohort order (idempotent).
        Must run before :meth:`mean` or any direct ``wsum`` consumer
        (secure-agg unmasking mutates ``wsum`` after this)."""
        if self._finalized:
            return
        self._finalized = True
        order = (self._order if self._order is not None
                 else sorted(self._staged))
        ids = [cid for cid in order if cid in self._staged]
        ids += [cid for cid in self._staged if cid not in ids]
        for cid in ids:
            w, contrib, loss_w = self._staged[cid]
            self.wsum = (
                contrib if self.wsum is None
                else pytrees.tree_add(self.wsum, contrib)
            )
            self.total_w += w
            self.loss_sum += loss_w
        self.folded_ids = ids
        self._staged.clear()

    def apply_correction(self, tree: Any) -> None:
        """Subtract a correction term from the finalized weighted sum —
        the secure-agg recovery hook: reconstructed self-masks and orphaned
        pair-mask halves are removed as ONE final term, never by
        densifying and re-summing the folded updates."""
        if not self._finalized:
            raise RuntimeError(
                "apply_correction requires a finalized fold (the "
                "correction is defined relative to the completed sum)"
            )
        if self.wsum is None:
            return
        if self._placement is not None:
            # Same per-shard layout as the staged contributions; the
            # subtraction runs slice-wise, elementwise-identical to the
            # full-leaf subtraction.
            tree = self._placement.slice_tree(tree)
        self.wsum = pytrees.tree_sub(self.wsum, tree)

    def mean(self) -> tuple[Optional[Any], float, float]:
        self.finalize()
        mean_delta, total_w, mean_loss = super().mean()
        if mean_delta is not None and self._placement is not None:
            # Per-shard slices → a sharded jax.Array tree: every device
            # receives exactly its own shard bytes, never the full leaf.
            mean_delta = self._placement.assemble(mean_delta)
        return mean_delta, total_w, mean_loss
