"""Weighted folding of client updates — shared by both socket coordinators.

The synchronous round loop (comm/coordinator.py) and the buffered
asynchronous aggregator (comm/async_coordinator.py) accumulate the same
thing: decompressed client deltas scaled by their aggregation weight, a
running weight total, and a weighted loss.  One helper keeps the two
planes' aggregation math identical (decompression, weighting, the guarded
zero-weight mean) — the host-side mirror of the engine's in-XLA
``tree_weighted_sum`` / ``_finish_round`` pair.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import numpy as np

from colearn_federated_learning_tpu.utils import pytrees


class UpdateFolder:
    """Accumulate weighted client deltas; ``mean()`` is None-safe."""

    def __init__(self, shapes: Any):
        self.shapes = shapes            # params-shaped numpy pytree
        self.wsum: Optional[Any] = None
        self.total_w = 0.0
        self.loss_sum = 0.0
        self.count = 0

    def add(self, meta: dict, delta: Any,
            weight: Optional[float] = None) -> float:
        """Fold one update.  ``weight`` overrides the worker-reported
        ``meta["weight"]`` (the async plane multiplies in its staleness
        discount).  Returns the weight actually applied."""
        from colearn_federated_learning_tpu.fed import compression

        delta = compression.decompress_delta(delta, meta, shapes=self.shapes)
        w = float(meta.get("weight", 1.0)) if weight is None else float(weight)
        contrib = pytrees.tree_scale(jax.tree.map(np.asarray, delta), w)
        self.wsum = (
            contrib if self.wsum is None
            else pytrees.tree_add(self.wsum, contrib)
        )
        self.total_w += w
        self.loss_sum += float(meta.get("mean_loss", 0.0)) * w
        self.count += 1
        return w

    def mean(self) -> tuple[Optional[Any], float, float]:
        """(mean_delta | None, total_weight, weighted_mean_loss).  A fold
        with zero total weight yields (None, 0, nan) — callers skip the
        server step rather than divide by zero."""
        if self.total_w <= 0.0:
            return None, 0.0, math.nan
        return (
            pytrees.tree_scale(self.wsum, 1.0 / self.total_w),
            self.total_w,
            self.loss_sum / self.total_w,
        )
