"""Tensor plane: request/reply pytree transport between coordinator and a
device (the PySyft ``WebsocketServerWorker`` equivalent, SURVEY.md §1
"Client runtime" / §3b).

A device hosts a ``TensorServer`` whose handler maps
``(header, pytree) -> (header, pytree)``; the coordinator's
``TensorClient`` does one round trip per request.  Payloads are
utils/serialization.py npz bytes — the same format the offline file flow
writes, so wire and file federation are interchangeable.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, Optional

from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.utils.serialization import (
    bytes_to_pytree,
    pytree_to_bytes,
)

Handler = Callable[[dict, Any], tuple[dict, Any]]


class TensorServer:
    """Serve ``handler`` on a TCP port (``port=0`` → ephemeral, see
    ``.port``).  One thread per connection; connections may issue many
    requests (the coordinator keeps one open across rounds)."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1",
                 port: int = 0):
        self._handler = handler
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()
        self._stopping = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()

    def start(self) -> "TensorServer":
        threading.Thread(target=self._accept_loop, name="tensor-accept",
                         daemon=True).start()
        return self

    def stop(self) -> None:
        """Stop accepting AND sever live connections — a stopped server
        must actually disappear from the federation, not linger on
        already-open sockets."""
        self._stopping.set()
        # A worker restarting on its own port must be able to rebind:
        # wake the blocked accept before closing (protocol.wake_accept).
        protocol.wake_accept(self.host, self.port)
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            # Re-check AFTER accept: some loopback shims deliver one more
            # connection even though the listener was closed by stop().
            if self._stopping.is_set():
                try:
                    conn.close()
                except OSError:
                    pass
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name="tensor-conn", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                header, body = protocol.recv_msg(conn)
                tree, meta = bytes_to_pytree(body) if body else (None, {})
                header.setdefault("meta", meta)
                try:
                    out_header, out_tree = self._handler(header, tree)
                except Exception as e:  # report, keep serving
                    out_header, out_tree = {"status": "error",
                                            "error": repr(e)}, None
                out_body = (
                    pytree_to_bytes(out_tree, out_header.pop("meta", None))
                    if out_tree is not None else b""
                )
                out_header.setdefault("status", "ok")
                protocol.send_msg(conn, out_header, out_body)
        except (protocol.ConnectionClosed, OSError, ValueError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass


class TensorClient:
    """Coordinator-side connection to one device's TensorServer."""

    def __init__(self, host: str, port: int, timeout: Optional[float] = None):
        self._sock = protocol.connect(host, port, timeout=timeout)

    def request(self, header: dict, tree: Any = None,
                meta: Optional[dict] = None,
                timeout: Optional[float] = None) -> tuple[dict, Any]:
        """One round trip.  Raises ``TimeoutError``/``OSError`` on a dead or
        too-slow peer — the coordinator treats that as a straggler drop."""
        self._sock.settimeout(timeout)
        body = pytree_to_bytes(tree, meta) if tree is not None else b""
        protocol.send_msg(self._sock, header, body)
        out_header, out_body = protocol.recv_msg(self._sock)
        out_tree, out_meta = bytes_to_pytree(out_body) if out_body else (None, {})
        out_header.setdefault("meta", out_meta)
        return out_header, out_tree

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
