"""Tensor plane: request/reply pytree transport between coordinator and a
device (the PySyft ``WebsocketServerWorker`` equivalent, SURVEY.md §1
"Client runtime" / §3b).

A device hosts a ``TensorServer`` whose handler maps
``(header, pytree) -> (header, pytree)``; the coordinator's
``TensorClient`` does one round trip per request.  Payloads are
utils/serialization.py npz bytes — the same format the offline file flow
writes, so wire and file federation are interchangeable.

Robustness seams (faults/ exercises both, production pays for neither
when they are off):

- an optional process-wide :class:`TransportInterposer` is consulted at
  each request/reply boundary — the fault-injection hook (install one via
  :func:`install_interposer`; ``None``, the default, is a single pointer
  check per message);
- ``TensorClient.request`` takes an optional :class:`RetryPolicy` plus a
  shared ``deadline``: transient failures (reset connections, corrupt
  frames) are retried on a FRESH socket with exponential backoff + full
  jitter, and every attempt is budgeted against the deadline so retries
  can never stack past the round's one timeout.  Peer timeouts are NOT
  retried — a peer that consumed the whole budget is a straggler, and
  re-asking cannot finish any sooner.
"""

from __future__ import annotations

import dataclasses
import random
import socket
import threading
import time
import zlib
from typing import Any, Callable, Optional

from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.telemetry import registry as _metrics
from colearn_federated_learning_tpu.utils.serialization import (
    bytes_to_pytree,
    pytree_to_bytes,
)

Handler = Callable[[dict, Any], tuple[dict, Any]]


class SkipRequest(Exception):
    """Raised by an interposer to make the server silently discard the
    current request — no reply, connection kept open.  The client-side
    symptom is a request timeout, exactly like a lost datagram."""


class TransportInterposer:
    """Hook points the transport consults when one is installed.

    The base class is a no-op; faults.FaultInjector overrides these to
    inject deterministic failures.  Hooks communicate through ordinary
    transport exceptions (``protocol.ConnectionClosed``, ``OSError``,
    :class:`SkipRequest`) or by writing to/closing the socket themselves,
    so the transport needs no fault-specific control flow."""

    def server_request(self, server: "TensorServer", conn: socket.socket,
                       header: dict) -> None:
        """After a request frame is received, before the handler runs."""

    def server_reply(self, server: "TensorServer", conn: socket.socket,
                     header: dict) -> None:
        """Before the reply frame is sent; ``header`` is the REQUEST's."""

    def client_request(self, client: "TensorClient", header: dict) -> None:
        """Before the client sends a request frame."""


_interposer: Optional[TransportInterposer] = None


def install_interposer(obj: Optional[TransportInterposer]) -> None:
    """Install (or with ``None`` remove) the process-wide interposer."""
    global _interposer
    _interposer = obj


def current_interposer() -> Optional[TransportInterposer]:
    return _interposer


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + full jitter (the AWS
    "full jitter" schedule: sleep ~ U(0, min(max, base·2^attempt))).
    ``max_retries`` counts RE-tries — 0 disables retrying entirely."""

    max_retries: int = 2
    backoff_base: float = 0.05
    backoff_max: float = 2.0

    def delay(self, attempt: int, rng: random.Random) -> float:
        cap = min(self.backoff_max, self.backoff_base * (2.0 ** attempt))
        return rng.uniform(0.0, cap)


class TensorServer:
    """Serve ``handler`` on a TCP port (``port=0`` → ephemeral, see
    ``.port``).  One thread per connection; connections may issue many
    requests (the coordinator keeps one open across rounds).

    ``ident`` names the hosted device (the worker's client id) so an
    installed interposer can key faults by ``(device_id, round, op)``."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1",
                 port: int = 0, ident: str = ""):
        self._handler = handler
        self.ident = ident
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()
        self._stopping = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()

    def start(self) -> "TensorServer":
        threading.Thread(target=self._accept_loop, name="tensor-accept",
                         daemon=True).start()
        return self

    def stop(self, wake_timeout: float = 1.0) -> None:
        """Stop accepting AND sever live connections — a stopped server
        must actually disappear from the federation, not linger on
        already-open sockets.  Close errors are survivable (the peer may
        have dropped first) but never silent: each is counted in
        ``comm.suppressed_oserrors_total``."""
        self._stopping.set()
        # A worker restarting on its own port must be able to rebind:
        # wake the blocked accept before closing (protocol.wake_accept).
        protocol.wake_accept(self.host, self.port, timeout=wake_timeout)
        protocol.close_quietly(self._srv)
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            protocol.close_quietly(c, shutdown=True)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                # Blocking by design: stop() always sends a wake_accept
                # connection, so this never outlives the server.
                conn, _ = self._srv.accept()  # colearn: noqa(CL002): stop() wakes the accept via a sentinel connect
            except OSError:
                return  # listener closed by stop()
            # Re-check AFTER accept: some loopback shims deliver one more
            # connection even though the listener was closed by stop().
            if self._stopping.is_set():
                protocol.close_quietly(conn)
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name="tensor-conn", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                header, body = protocol.recv_msg(conn)
                ip = _interposer
                try:
                    if ip is not None:
                        ip.server_request(self, conn, header)
                except SkipRequest:       # colearn: noqa(CL003): interposer-ordered drop, counted at the seam
                    continue              # request "lost" BY DESIGN: the
                    # interposer asked for a drop; no reply at all
                tree, meta = bytes_to_pytree(body) if body else (None, {})
                header.setdefault("meta", meta)
                try:
                    out_header, out_tree = self._handler(header, tree)
                except Exception as e:  # report, keep serving
                    out_header, out_tree = {"status": "error",
                                            "error": repr(e)}, None
                out_body = (
                    pytree_to_bytes(out_tree, out_header.pop("meta", None))
                    if out_tree is not None else b""
                )
                out_header.setdefault("status", "ok")
                if ip is not None:
                    ip.server_reply(self, conn, header)
                protocol.send_msg(conn, out_header, out_body)
        except protocol.ConnectionClosed:  # colearn: noqa(CL003): peer hangup is normal teardown
            pass                           # normal peer disconnect
        except (OSError, ValueError):
            protocol.count_suppressed()  # flaky/buggy peer; drop it
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            protocol.close_quietly(conn)


# Failure classes a retry can actually fix: the peer is (or may be) alive
# but THIS exchange died — reset/refused connections, a mid-frame close,
# a corrupt frame.  TimeoutError (a subclass of OSError since 3.10) is
# excluded by an explicit re-raise in the retry loop.
_RETRYABLE = (protocol.ConnectionClosed, protocol.CorruptFrame, OSError)


class TensorClient:
    """Coordinator-side connection to one device's TensorServer.

    ``ident`` names the PEER device; it keys interposer faults and seeds
    this client's deterministic retry jitter."""

    def __init__(self, host: str, port: int, timeout: Optional[float] = None,
                 ident: str = ""):
        self._host, self._port = host, port
        self.ident = ident or f"{host}:{port}"
        self._rng = random.Random(zlib.crc32(self.ident.encode()))
        self.closed = False
        # Backoff sleeps wait on this instead of time.sleep so close()
        # wakes a mid-backoff retrier immediately (CL015).
        self._closing = threading.Event()
        self._sock = protocol.connect(host, port, timeout=timeout)

    def _reconnect(self, timeout: Optional[float]) -> None:
        protocol.close_quietly(self._sock)
        if self.closed:
            # An abandoned fan-out ask must not resurrect a connection the
            # coordinator already replaced: its ghost request would hit the
            # worker concurrently with the next round's on the new client.
            raise protocol.ConnectionClosed(f"{self.ident}: client closed")
        self._sock = protocol.connect(self._host, self._port, timeout=timeout)

    def request(self, header: dict, tree: Any = None,
                meta: Optional[dict] = None,
                timeout: Optional[float] = None,
                retry: Optional[RetryPolicy] = None,
                deadline: Optional[float] = None,
                body: Any = None) -> tuple[dict, Any]:
        """One round trip.  Raises ``TimeoutError``/``OSError`` on a dead or
        too-slow peer — the coordinator treats that as a straggler drop.

        ``body`` is an optional PRE-ENCODED CLW1 frame (any bytes-like,
        shared read-only across calls): the serialize-once broadcast path.
        The coordinator encodes the round's params frame once and hands the
        same buffer to every cohort send, instead of paying a full-model
        encode + crc32 per device per round here.  Mutually exclusive with
        ``tree``/``meta``.

        With ``retry``, transient transport failures are retried on a
        fresh socket (a failed socket may hold a late half-frame that
        would desynchronise the stream).  ``deadline`` is an absolute
        ``time.monotonic()`` instant shared by every attempt AND backoff
        sleep, so retrying never extends the caller's one budget."""
        if body is None:
            body = pytree_to_bytes(tree, meta) if tree is not None else b""
        elif tree is not None:
            raise ValueError("pass either a pre-encoded body or a tree, "
                             "not both")
        if self.closed:
            raise protocol.ConnectionClosed(f"{self.ident}: client closed")
        attempts = 1 + (retry.max_retries if retry is not None else 0)
        # Labeled per peer: the aggregate still counts every retry, and
        # the {device=...} children answer "who is flaky?" in snapshots.
        retries = _metrics.get_registry().counter(
            "comm.retry_total", labels={"device": self.ident})
        for attempt in range(attempts):
            attempt_timeout = timeout
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"{self.ident}: round deadline exhausted before "
                        f"attempt {attempt + 1}"
                    )
                attempt_timeout = (remaining if attempt_timeout is None
                                   else min(attempt_timeout, remaining))
            try:
                ip = _interposer
                if ip is not None:
                    ip.client_request(self, header)
                self._sock.settimeout(attempt_timeout)
                protocol.send_msg(self._sock, header, body)
                out_header, out_body = protocol.recv_msg(self._sock)
                break
            except TimeoutError:
                raise                    # straggler: retrying cannot help
            except _RETRYABLE:
                if attempt + 1 >= attempts:
                    raise
                retries.inc()
                delay = retry.delay(attempt, self._rng)
                if deadline is not None:
                    delay = min(delay, max(0.0, deadline - time.monotonic()))
                if delay > 0 and self._closing.wait(delay):
                    # close() fired mid-backoff: abort instead of
                    # reconnecting onto a socket the owner gave up on.
                    raise protocol.ConnectionClosed(
                        f"{self.ident}: client closed during retry backoff")
                # Reconnect may itself fail (peer rebooting): that is the
                # next attempt's failure, charged against the same budget.
                try:
                    self._reconnect(attempt_timeout)
                except TimeoutError:
                    raise
                except _RETRYABLE:
                    if attempt + 2 >= attempts:
                        raise
        out_tree, out_meta = bytes_to_pytree(out_body) if out_body else (None, {})
        out_header.setdefault("meta", out_meta)
        return out_header, out_tree

    def close(self) -> None:
        # Flag BEFORE closing: a concurrent (abandoned) request that hits
        # the dying socket sees the flag and aborts instead of retrying
        # onto a fresh connection.
        self.closed = True
        self._closing.set()
        protocol.close_quietly(self._sock)
