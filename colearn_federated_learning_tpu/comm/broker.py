"""Tiny TCP pub/sub broker — the control plane's MQTT stand-in.

The reference enrolls devices through an external MQTT broker (paho-mqtt
``on_connect``/``on_message`` handlers, SURVEY.md §2 "MQTT enrollment
manager").  The rebuild ships its own dependency-free broker speaking the
framing in protocol.py:

- ``{"op": "sub", "topic": t}``  — subscribe this connection to ``t``;
  a trailing ``#`` subscribes to the whole prefix (MQTT-style wildcard).
- ``{"op": "pub", "topic": t, ...}`` + body — fan out to all subscribers.
- Messages retain their extra header fields and body verbatim.

Topics with a retained last message (``"retain": true`` on publish) replay
it to late subscribers — used for role assignments so a device that
subscribes after selection still learns its role.
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Optional

from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.faults import lockwitness


def _match(pattern: str, topic: str) -> bool:
    if pattern.endswith("#"):
        return topic.startswith(pattern[:-1])
    return pattern == topic


class MessageBroker:
    """Threaded pub/sub broker on localhost.  ``port=0`` picks a free port
    (read it back from ``.port``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self._lock = lockwitness.lock("broker.lock")
        self._subs: dict[socket.socket, list[str]] = lockwitness.guarded(
            {}, "broker._subs", self._lock)  # colearn: guarded-by(_lock)
        # Per-socket write locks: publisher threads fan out concurrently and
        # protocol frames must never interleave on a subscriber's stream.
        self._wlocks: dict[socket.socket, threading.Lock] = {}
        self._retained: dict[str, tuple[dict, bytes]] = {}
        self._stopping = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> "MessageBroker":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="broker-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop(self, wake_timeout: float = 1.0) -> None:
        self._stopping.set()
        protocol.wake_accept(self.host, self.port, timeout=wake_timeout)
        protocol.close_quietly(self._srv)
        with self._lock:
            # Every accepted connection (tracked by its write lock), not
            # just the subscribed ones — a stopped broker must sever
            # clients that connected but never subscribed too.
            socks = set(self._subs) | set(self._wlocks)
            self._subs.clear()
            self._wlocks.clear()
        for s in socks:
            # shutdown BEFORE close: close() alone does not interrupt a
            # serve thread blocked in recv (the in-flight syscall pins the
            # kernel socket), so no FIN would reach the peer and clients
            # could never detect the broker's death.
            protocol.close_quietly(s, shutdown=True)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                # Blocking by design: stop() always sends a wake_accept
                # connection, so this never outlives the broker.
                conn, _ = self._srv.accept()  # colearn: noqa(CL002): stop() wakes the accept via a sentinel connect
            except OSError:
                return  # listener closed by stop()
            # Re-check AFTER accept: some loopback shims deliver one more
            # connection even though the listener was closed by stop().
            if self._stopping.is_set():
                protocol.close_quietly(conn)
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._wlocks[conn] = threading.Lock()
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="broker-conn", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                header, body = protocol.recv_msg(conn)
                op = header.get("op")
                if op == "sub":
                    self._subscribe(conn, header["topic"],
                                    ack=bool(header.get("ack")))
                elif op == "pub":
                    self._publish(header, body)
                elif op == "ping":
                    self._send(conn, {"op": "pong"}, b"")
        except protocol.ConnectionClosed:  # colearn: noqa(CL003): client hangup is normal teardown
            pass                           # normal client disconnect
        except (OSError, ValueError):
            protocol.count_suppressed()  # flaky/buggy peer; drop it
        finally:
            with self._lock:
                self._subs.pop(conn, None)
                self._wlocks.pop(conn, None)
            protocol.close_quietly(conn)

    def _send(self, conn: socket.socket, header: dict, body: bytes) -> None:
        with self._lock:
            wlock = self._wlocks.get(conn)
        if wlock is None:
            return
        try:
            with wlock:
                protocol.send_msg(conn, header, body)
        except OSError:
            # A dead subscriber must not break fan-out to the others; its
            # serve thread reaps it on the next recv.
            protocol.count_suppressed()

    def _subscribe(self, conn: socket.socket, pattern: str,
                   ack: bool = False) -> None:
        """Register ``pattern`` (idempotent: re-subscribes replay retained
        messages — MQTT semantics — without growing the subscription
        list) and, when ``ack``, follow the replay with a ``suback``
        frame so the client KNOWS the replay is complete — how
        enrollment.fetch_device_info distinguishes the current retained
        record from stale leftovers in its queue."""
        with self._lock:
            pats = self._subs.setdefault(conn, [])
            if pattern not in pats:
                pats.append(pattern)
            replay = [
                (dict(h), b) for t, (h, b) in self._retained.items()
                if _match(pattern, t)
            ]
        for h, b in replay:
            self._send(conn, h, b)
        if ack:
            self._send(conn, {"op": "suback", "topic": pattern}, b"")

    def _publish(self, header: dict, body: bytes) -> None:
        topic = header["topic"]
        out = {k: v for k, v in header.items() if k not in ("op", "retain")}
        out["op"] = "msg"
        with self._lock:
            if header.get("retain"):
                self._retained[topic] = (out, body)
            targets = [
                s for s, pats in self._subs.items()
                if any(_match(p, topic) for p in pats)
            ]
        for s in targets:
            self._send(s, out, body)


class BrokerClient:
    """One connection to the broker: publish anywhere, receive subscribed
    messages via ``recv(timeout=...)``.

    A dedicated reader thread drains frames into a queue, so a consumer
    timeout can NEVER strand the socket mid-frame (a plain socket timeout
    inside a length-prefixed read would desynchronise the stream for
    good)."""

    def __init__(self, host: str, port: int, timeout: Optional[float] = None):
        self._sock = protocol.connect(host, port, timeout=timeout)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._dead = threading.Event()
        self._q: "queue.Queue[Optional[tuple[dict, bytes]]]" = queue.Queue()
        self._reader = threading.Thread(
            target=self._read_loop, name="broker-client-read", daemon=True
        )
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                self._q.put(protocol.recv_msg(self._sock))
        except (protocol.ConnectionClosed, OSError, ValueError):
            self._dead.set()
            self._q.put(None)                 # sentinel: connection is gone

    def alive(self) -> bool:
        """False once the broker connection died (the reader thread exited)
        — the worker watchdog's restart-detection signal.  Queued messages
        received before the death are still drainable via ``recv``."""
        return not self._dead.is_set()

    def subscribe(self, topic: str, ack: bool = False) -> None:
        """``ack=True`` asks the broker to append a ``suback`` frame after
        the retained replay (see MessageBroker._subscribe)."""
        header = {"op": "sub", "topic": topic}
        if ack:
            header["ack"] = True
        with self._wlock:
            protocol.send_msg(self._sock, header)

    def publish(self, topic: str, fields: Optional[dict] = None,
                body: bytes = b"", retain: bool = False) -> None:
        header = {"op": "pub", "topic": topic, **(fields or {})}
        if retain:
            header["retain"] = True
        with self._wlock:
            protocol.send_msg(self._sock, header, body)

    def recv(self, timeout: Optional[float] = None) -> tuple[dict, bytes]:
        """Next message on any subscribed topic.  Raises ``TimeoutError``
        after ``timeout`` seconds, ``ConnectionClosed`` on a dead broker."""
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError("no broker message") from None
        if item is None:
            raise protocol.ConnectionClosed("broker connection closed")
        return item

    def close(self) -> None:
        protocol.close_quietly(self._sock)
