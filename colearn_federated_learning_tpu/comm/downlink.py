"""Downlink delta compression for the socket broadcast (wire fast path).

The uplink has compressed client deltas since fed/compression.py landed,
but the coordinator still shipped FULL uncompressed params to every
cohort member every round — at the IoT edge the downlink is half the
round's bytes.  This module closes that gap bidirectionally (the Aji &
Heafield 2017 update-compression direction, PAPERS.md):

- the coordinator broadcasts the SERVER DELTA (params_r − base_{r-1})
  through the existing ``int8``/``topk`` codecs (``FedConfig
  .compress_down``; ``none`` — the default — keeps the wire byte-identical
  to the pre-compression build);
- every worker caches the last global params it applied, keyed by round
  (:class:`WorkerParamCache`), and reconstructs ``base + delta``;
- the codecs are lossy, so the coordinator tracks the RECONSTRUCTED
  params the workers actually hold and diffs against THOSE (implicit
  error feedback: this round's quantization residual rides into the next
  round's delta instead of accumulating as silent drift);
- a cache miss or round gap (worker restart, re-enrollment, a
  flap/drop that skipped a round — any faults/ scenario) makes the worker
  reply ``status="resync"`` and the coordinator re-send the full
  reconstructed params for the round, so every worker converges on the
  SAME bytes no matter how it rejoined.  Resyncs are counted in
  ``comm.resync_total``; per-send byte savings in
  ``comm.bytes_saved_downlink``.

Synchronous-coordinator only: the async dispatcher pumps run one model
version per device with no shared base, so they broadcast full params
(still serialize-once per version).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import jax
import numpy as np

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.fed import compression
from colearn_federated_learning_tpu.parallel import partition
from colearn_federated_learning_tpu.utils.serialization import (
    pytree_to_bytes,
    wire_frame_length,
)

# Broadcast meta slots (CLW1 frame meta, alongside "round").
DOWN_KEY = "down"            # "full" | "delta"; absent = plain broadcast
DOWN_BASE_KEY = "down_base"  # round whose cached params the delta is against
MODE_FULL = "full"
MODE_DELTA = "delta"


def apply_dense_delta(base: Any, delta: Any) -> Any:
    """``base + delta`` leafwise, float32 accumulation, base dtypes kept
    (decompressed deltas are float32; params may be bfloat16).  The
    coordinator and every worker run this SAME function on identical
    arrays, so their reconstructions agree bitwise."""
    def add(b, d):
        b = np.asarray(b)
        return (b.astype(np.float32)
                + np.asarray(d, np.float32)).astype(b.dtype)

    return jax.tree.map(add, base, delta)


def host_params(tree: Any) -> Any:  # colearn: hot
    """Wire-side host view of the server params — the gather-free path.

    Sharded ``jax.Array`` leaves (the PR 9 sharded server) are read
    PER-SHARD straight off their devices into each leaf's host buffer
    (``parallel.partition.host_leaf``): no device-side all-gather ever
    materializes a replicated copy, no full-tree ``jax.device_get`` runs,
    and on a multi-host mesh this is the only legal read.  The bytes the
    per-chip replicated layout would have required are counted in
    ``comm.gather_bytes_avoided_total``.  Host numpy trees (the replicated
    coordinator) pass through byte-identically.
    """
    avoided = partition.tree_gather_avoided(tree)
    if avoided:
        telemetry.get_registry().counter(
            "comm.gather_bytes_avoided_total").inc(avoided)
    return partition.host_tree(tree)


class DownlinkEncoder:
    """Per-round broadcast encoder (coordinator side): one CLW1 encode per
    round — counted in ``comm.broadcast_encode_total`` — whose frame is
    shared read-only across every cohort send (serialize-once)."""

    def __init__(self, scheme: str = "none"):
        if scheme not in compression.SCHEMES:
            raise ValueError(
                f"unknown compress_down {scheme!r} "
                f"(use {compression.SCHEMES})"
            )
        self.scheme = scheme
        # (round, reconstructed params) — what the workers' caches hold.
        self._base: Optional[tuple[int, Any]] = None

    def encode_round(
        self, r: int, params_np: Any
    ) -> tuple[memoryview, Optional[Callable[[], memoryview]], int]:
        """Encode round ``r``'s broadcast body.

        Returns ``(body, resync_body, bytes_saved_per_send)``:
        ``body`` is the shared frame every cohort send uses; ``resync_body``
        (None when the scheme is off) lazily encodes — at most once — the
        full reconstructed params for workers that answered "resync";
        ``bytes_saved_per_send`` is the payload shrink a delta send
        realizes over a full-params send.

        ``params_np`` may be host numpy (replicated coordinator) or a
        sharded ``jax.Array`` tree (sharded server): sharded leaves are
        encoded from their device shards via :func:`host_params` — the
        resulting frame is byte-for-byte the frame the gathered tree
        would have produced (tests pin this)."""
        reg = telemetry.get_registry()
        params_np = host_params(params_np)
        if self.scheme == "none":
            # Byte-identical to the per-request encode this path replaced.
            body = pytree_to_bytes(params_np, {"round": r})
            reg.counter("comm.broadcast_encode_total").inc()
            return memoryview(body), None, 0

        if self._base is None:
            meta = {"round": r, DOWN_KEY: MODE_FULL}
            body = pytree_to_bytes(params_np, meta)
            reg.counter("comm.broadcast_encode_total").inc()
            self._base = (r, params_np)
            return memoryview(body), self._resync_fn(r, params_np), 0

        base_round, base = self._base
        delta = jax.tree.map(
            lambda a, b: np.asarray(a, np.float32) - np.asarray(b, np.float32),
            params_np, base,
        )
        wire, cmeta = compression.compress_delta(delta, self.scheme)
        meta = {"round": r, DOWN_KEY: MODE_DELTA, DOWN_BASE_KEY: base_round,
                **cmeta}
        body = pytree_to_bytes(wire, meta)
        reg.counter("comm.broadcast_encode_total").inc()
        recon = apply_dense_delta(
            base, compression.decompress_delta(wire, cmeta, shapes=base)
        )
        self._base = (r, recon)
        # Frame-vs-frame: what a full-params broadcast WOULD have cost on
        # the wire this round, minus what the delta frame actually costs.
        full_len = wire_frame_length(
            params_np, {"round": r, DOWN_KEY: MODE_FULL})
        saved = max(0, full_len - len(body))
        return memoryview(body), self._resync_fn(r, recon), saved

    def _resync_fn(self, r: int, recon: Any) -> Callable[[], memoryview]:
        """Lazy one-shot encoder for the round's full-params resync body.
        Encoded only if some worker actually needs it, at most once per
        round (concurrent resyncs share the frame), and it ships the
        RECONSTRUCTED params — the exact bytes the rest of the cohort
        derived — so a rejoining worker's cache matches its peers'."""
        lock = threading.Lock()
        cache: list[memoryview] = []

        def resync_body() -> memoryview:
            with lock:
                if not cache:
                    telemetry.get_registry().counter(
                        "comm.broadcast_encode_total").inc()
                    cache.append(memoryview(pytree_to_bytes(
                        recon, {"round": r, DOWN_KEY: MODE_FULL})))
                return cache[0]

        return resync_body


class WorkerParamCache:
    """Worker-side cache of the last applied global params, keyed by
    round.  ``resolve`` returns the round's full params (applying a delta
    against the cache when the broadcast is compressed) or ``None`` when
    the worker must request a full-params resync."""

    def __init__(self) -> None:
        self._round: Optional[int] = None
        self._params: Any = None

    def resolve(self, round_idx: int, meta: dict, tree: Any) -> Any:
        mode = meta.get(DOWN_KEY)
        if mode == MODE_DELTA:
            if self._round == round_idx:
                # Transport retry of a round we already applied (the reply
                # was lost, not the request): idempotent.
                return self._params
            base = meta.get(DOWN_BASE_KEY)
            if self._params is None or self._round != base:
                return None          # restart / skipped round → resync
            delta = compression.decompress_delta(
                tree, meta, shapes=self._params
            )
            params = apply_dense_delta(self._params, delta)
            self._round, self._params = round_idx, params
            return params
        # MODE_FULL (or a plain broadcast while caching is active).
        params = jax.tree.map(np.asarray, tree)
        self._round, self._params = round_idx, params
        return params
