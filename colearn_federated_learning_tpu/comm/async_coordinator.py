"""Asynchronous (buffered) federated coordinator over the socket planes.

The reference's round loop — like the synchronous coordinator here
(comm/coordinator.py, SURVEY.md §3a) — is BULK-synchronous: every round
waits on a deadline for the whole cohort, so one slow device stalls the
federation.  This coordinator is the buffered-asynchronous alternative
(FedBuff lineage — Nguyen et al. 2106.06639, PAPERS.md pattern only):

- one dispatcher thread per trainer keeps that device continuously busy:
  snapshot the CURRENT global model, request local training, enqueue the
  returned delta tagged with the model version it started from;
- the aggregator applies the buffer as soon as ``buffer_size`` updates
  arrive — no deadline, no stragglers: a slow device just contributes to a
  later aggregation with a staleness discount;
- staleness weighting: an update trained on version ``v`` applied at
  version ``t`` is scaled by ``(1 + t - v)^(-staleness_exponent)``
  (FedBuff's 1/sqrt(1+τ) at the default 0.5), and updates older than
  ``max_staleness`` are discarded outright;
- the server step reuses the SAME fed/strategies.py update the jit engine
  and the synchronous coordinator use.

Workers are completely unchanged: a train request carries the model
version in the ``round`` field, and the worker's per-(client, round) PRNG
keys make its minibatch stream deterministic per version.

DP composes with the buffered path: every APPLIED aggregation is charged
to the RDP accountant as one Gaussian mechanism at its realized effective
multiplier — the staleness weights enter the sensitivity/noise ratio
exactly (see ``_charge_privacy``), q = 1 (no subsampling-amplification
claim: buffer membership is availability-ordered), and discarded updates
charge nothing (never released).  Restore replays each record's charged
multiplier.  ``secure_agg`` stays synchronous-only (masks need an agreed
per-round cohort), as does adaptive clipping (cross-round engine state).

Health-driven straggler pruning (CLIP lineage — arXiv 2510.16694,
PAPERS.md): with a health ledger attached (``run.health_dir``) every
dispatch outcome is attributed per device — observed latency on success,
a retry count on failure, a deadline miss on every ``max_staleness``
discard — and the coordinator scores devices from that ledger plus its
own consecutive-too-stale streaks.  A chronic straggler's updates are
predestined for the staleness discard, so its pump is PAUSED (a pruned
client is a predicted dropout that stops burning device compute) and
re-admitted after a probation window of aggregations.  Pruning never
shrinks the active pump set below ``buffer_size`` (the buffer must stay
fillable), and all of it is off — with byte-identical aggregation
records — unless explicitly enabled.

The staleness observatory (PR 14) makes the plane observable and then
load-bearing:

- **version lineage:** every enqueued update carries its
  ``dispatch_train`` span context, and the aggregator folds it inside a
  ``fold_update`` span PARENTED on that context — so one Perfetto trace
  per update shows dispatch → worker train → buffer-wait → fold, with τ
  and the owning ``async.aggregate`` span id in the args (the PR 12
  tree-stitch pattern, per update instead of per tier);
- **staleness & pump telemetry:** a labeled
  ``async.staleness{outcome=folded|discarded}`` histogram, buffer
  occupancy / per-pump-state gauges, a seeded-EWMA arrival-rate
  estimator (telemetry/arrival.py, fleet + per-device gauges), and
  contribution-mass accounting (Σ(1+τ)^-exp folded vs. discarded);
- **adaptive buffering:** ``buffer_size="auto"`` retunes K from the
  observed fleet arrival rate before every aggregation (K = rate ×
  target fold interval, clamped to [1, trainers]) — the ROADMAP's
  "K driven by the observed arrival rate instead of a flag".

Observatory record keys (mass/arrival/staleness-tail) are stamped only
when ``observe`` (or auto-K) is on; default records stay byte-identical.
"""

from __future__ import annotations

import math
import os
import queue
import threading
import time
from typing import Optional

import jax
import numpy as np

from colearn_federated_learning_tpu.comm.broker import BrokerClient
from colearn_federated_learning_tpu.comm.enrollment import (
    DeviceInfo,
    EnrollmentManager,
)
from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.comm.transport import TensorClient
from colearn_federated_learning_tpu.faults import lockwitness
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.utils.config import (
    ExperimentConfig,
    validate_robustness,
)


class AsyncFederatedCoordinator:
    """Buffered-asynchronous aggregation server (see module docstring)."""

    def __init__(
        self,
        config: ExperimentConfig,
        broker_host: str,
        broker_port: int,
        buffer_size=4,
        staleness_exponent: float = 0.5,
        max_staleness: int = 10,
        request_timeout: float = 60.0,
        want_evaluator: bool = True,
        mud_policy=None,
        prune_after: int = 0,
        prune_score: float = 0.0,
        probation: int = 8,
        observe: bool = False,
        auto_interval_s: float = 2.0,
    ):
        """``prune_after``: consecutive too-stale discards before a
        device's pump is paused (0 disables streak pruning).
        ``prune_score``: health-ledger score threshold that pauses a pump
        (0 disables score pruning).  ``probation``: aggregations a pruned
        device sits out before re-admission.  Either pruning trigger
        requires ``run.health_dir`` — the ledger is the score source.
        ``buffer_size``: an int, or ``"auto"`` to size K from the
        observed arrival rate (K = rate × ``auto_interval_s``, the target
        fold cadence, re-evaluated before every aggregation).
        ``observe``: stamp observatory keys (contribution mass, arrival
        rate, staleness tail) into aggregation records; implied by
        auto-K, off by default so default records stay byte-identical."""
        if isinstance(buffer_size, str):
            if buffer_size != "auto":
                raise ValueError(
                    f"buffer_size must be an int >= 1 or 'auto', "
                    f"got {buffer_size!r}")
            self.auto_buffer = True
            buffer_size = 4       # warm-start K until the estimator is live
        else:
            self.auto_buffer = False
            if buffer_size < 1:
                raise ValueError(
                    f"buffer_size must be >= 1, got {buffer_size}")
        if auto_interval_s <= 0:
            raise ValueError(
                f"auto_interval_s must be > 0, got {auto_interval_s}")
        if prune_after < 0 or prune_score < 0:
            raise ValueError("prune_after/prune_score must be >= 0")
        if probation < 1:
            raise ValueError(f"probation must be >= 1, got {probation}")
        if (prune_after or prune_score) and not config.run.health_dir:
            raise ValueError(
                "straggler pruning scores devices from the health ledger; "
                "set run.health_dir (--health-dir) to enable it"
            )
        if config.fed.dp_adaptive_clip:
            raise NotImplementedError(
                "dp_adaptive_clip is engine-only (stateless socket "
                "participants carry no cross-round clip state); use a "
                "fixed dp_clip for async DP"
            )
        if config.fed.secure_agg:
            raise NotImplementedError(
                "asynchronous aggregation with secure_agg is unsupported: "
                "pairwise masks need an agreed per-round cohort, and the "
                "dropout-recovery share distribution (privacy/dropout.py) "
                "is a round-scoped synchronous fan-out the per-device "
                "pumps don't have; use the synchronous coordinator"
            )
        if config.fed.compress_down != "none":
            raise NotImplementedError(
                "downlink delta compression (compress_down) is "
                "synchronous-only: each async pump trains a different "
                "model version, so there is no shared broadcast base to "
                "delta against; use the synchronous coordinator"
            )
        setup_lib.require_mean_aggregator(config, "the async coordinator")
        validate_robustness(config)
        self.config = config
        # Quorum, async flavor: an aggregation applied from fewer DISTINCT
        # devices than ceil(fraction × trainers) is discarded (see
        # run_aggregation) — a buffer filled by one fast device across
        # versions is not a federation round.  0 disables.
        self.min_cohort_fraction = config.fed.min_cohort_fraction
        self.buffer_size = buffer_size
        self.observe_records = bool(observe) or self.auto_buffer
        self.auto_interval_s = float(auto_interval_s)
        # Convergence observatory (telemetry/convergence.py): aggregate-
        # level learning signals per applied buffer — a staleness-
        # poisoned run shows up as oscillation/divergence long before the
        # final loss does.  Gated on run.learn_observe; default records
        # stay byte-identical (pinned by test).
        self._learn = None
        if config.run.learn_observe:
            self._learn = telemetry.ConvergenceObservatory()
        # Seeded-EWMA arrival-rate estimator (telemetry/arrival.py): the
        # pumps observe every successful dispatch on the monotonic clock;
        # auto-K and the per-aggregation gauges read the fleet rate.
        self.arrival = telemetry.ArrivalEstimator()
        # Per-pump state for the pump-state gauges (advisory — pumps
        # update their own slot; the aggregator counts them per agg).
        self._pump_state: dict[str, str] = {}
        # Cumulative fold/discard counts: auto-K scales the target
        # interval by the fold fraction (only FOLDED arrivals fill the
        # buffer, so sizing off raw arrivals overshoots when staleness
        # discards bite).
        self._folded_total = 0
        self._discarded_total = 0
        self.staleness_exponent = staleness_exponent
        self.max_staleness = max_staleness
        self.request_timeout = request_timeout
        self.want_evaluator = want_evaluator
        self._broker = BrokerClient(broker_host, broker_port,
                                    timeout=protocol.CONNECT_TIMEOUT)
        self._mud_policy = mud_policy
        self._enroll = EnrollmentManager(self._broker, mud_policy=mud_policy)
        params = setup_lib.init_global_params(config)
        # Sharded server (PR 9): with run.tp_size > 1 the global model and
        # the streaming fold live sharded over a local (model,) mesh —
        # same placement seam as the synchronous coordinator, same
        # counted fallback when the host cannot honor tp_size.
        from colearn_federated_learning_tpu.parallel import (
            partition as partition_lib,
        )

        self._placement = partition_lib.make_server_placement(
            params, config.run.tp_size, config.run.tp_axis,
            config.model.name,
        )
        if self._placement is not None:
            params = self._placement.shard(params)
            self._shapes_np = self._placement.shapes_tree()
        else:
            # Zero-memory shape/dtype stand-in (read-only broadcast
            # views) for folder construction.
            self._shapes_np = jax.tree.map(
                lambda a: np.broadcast_to(
                    np.zeros((), np.dtype(getattr(a, "dtype", np.float32))),
                    np.shape(a)),
                params,
            )
        # --fold-device: buffer folds run through the fused device kernel
        # (ops/fold_kernel.py); the host fold stays the parity oracle.
        self._fold_device = bool(getattr(config.run, "fold_device", False))
        self.server_state = strategies.init_server_state(params, config.fed)
        if self._placement is not None:
            telemetry.get_registry().gauge(
                "comm.server_bytes_per_chip").set(
                    partition_lib.bytes_per_chip(self.server_state))
        self.version = 0                       # server model version t
        self.history: list[dict] = []
        self.trainers: list[DeviceInfo] = []
        self.evaluator: Optional[DeviceInfo] = None
        self._clients: dict[str, TensorClient] = {}
        self._results: queue.Queue = queue.Queue()
        # (version, params_np, encoded body) — every pump dispatching model
        # version v shares ONE encoded frame (serialize-once per version).
        self._snap_cache: Optional[tuple] = None
        self._state_lock = lockwitness.lock("coord.state_lock")
        self._version_cv = lockwitness.condition("coord.version_cv")
        self._cv_poll_s = 0.1
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.failures: dict[str, int] = {}
        self._ckpt = None
        self.tracer = telemetry.Tracer(process="async-coordinator")
        # Per-device health ledger (telemetry/health.py): durable
        # straggler attribution fed from the dispatcher pumps (latency on
        # success, retries on failure) and the aggregator (staleness
        # discards as deadline misses).  Gated on run.health_dir; the
        # pump threads share one ledger, hence the lock.
        self.health = None
        self._health_lock = lockwitness.lock("coord.health_lock")
        self._health_retry_seen: dict[str, float] = {}
        if config.run.health_dir:
            self.health = telemetry.HealthLedger(config.run.health_dir,
                                                 "async-coordinator")
        # Straggler pruning state (see module docstring): paused pumps
        # keyed by device -> aggregation index at which probation ends.
        self.prune_after = int(prune_after)
        self.prune_score = float(prune_score)
        self.probation = int(probation)
        self.prune_enabled = bool(prune_after or prune_score)
        self._pruned: dict[str, int] = {}
        self._stale_streak: dict[str, int] = {}
        # Dead-pump eviction (RunConfig.evict_after): a pump whose device
        # fails this many CONSECUTIVE dispatches stops and revokes the
        # trainer instead of retrying forever.  Elastic re-enrollment
        # restarts the pump if the device comes back.
        self.evict_after = config.run.evict_after
        self._fail_streak: dict[str, int] = {}
        self.evicted: list[str] = []
        self._evicted_pending: list[str] = []
        # Async DP accounting: q = 1 (NO amplification-by-subsampling —
        # buffer membership is availability-ordered, not uniformly
        # sampled); each APPLIED aggregation is charged as one Gaussian
        # mechanism at its realized effective multiplier
        # (see _charge_privacy).
        from colearn_federated_learning_tpu.privacy.accountant import (
            RdpAccountant,
        )

        self.accountant = RdpAccountant.from_config(config.fed,
                                                    sampling_rate=1.0)
        # ---- buffered-async aggregator tree (tree mode) ------------------
        # With run.num_aggregators > 0 the pumps stop feeding the local
        # results queue and instead stream each contribution to its
        # assigned aggregator's per-slice buffer ("abuf"); one drainer
        # thread per aggregator long-polls partial folds back ("adrain")
        # and run_aggregation resolves staleness at the root against each
        # partial's OLDEST constituent version.  All of it is off — and
        # every queue/thread below inert — in the default flat mode.
        self.num_aggregators = int(config.run.num_aggregators)
        self.tree_mode = self.num_aggregators > 0
        self.agg_interval_s = float(config.run.agg_buffer_interval_s)
        self._broker_addr = (broker_host, broker_port)
        self._agg_lock = lockwitness.lock("coord.agg_lock")
        self._aggs: dict[int, dict] = lockwitness.guarded(
            {}, "coord._aggs", self._agg_lock)  # colearn: guarded-by(_agg_lock)
        # I/O-serialization gate for _refresh_aggs: try-acquired (never
        # blocked on, never nested) so broker RPC happens under no lock.
        self._agg_refreshing = lockwitness.lock("coord.agg_refreshing")
        self._agg_sub: Optional[BrokerClient] = None
        # Sticky-dead addresses: once an aggregator PROCESS (host, port)
        # is declared dead, nothing is ever drained from that address
        # again — with per-key idempotent staging and re-home-from-dead-
        # only, this is what makes double folds impossible.  A restarted
        # aggregator announces on a fresh port with an empty buffer.
        self._dead_addrs: set = set()
        self._dead_aggs: set = set()
        self._assign: dict[str, int] = {}       # device -> agg_id
        self._inflight_lock = lockwitness.lock("coord.inflight_lock")
        # dedup key -> contribution
        self._inflight: dict[str, tuple] = lockwitness.guarded(
            {}, "coord._inflight",
            self._inflight_lock)  # colearn: guarded-by(_inflight_lock)
        self._partials: queue.Queue = queue.Queue()
        self._drainers: list[threading.Thread] = []
        self._failovers_pending = 0
        self._rehomed_pending: set = set()
        self._rehomed_total = 0

    # ------------------------------------------------------------------
    def enroll(self, min_devices: int, timeout: float = 30.0) -> None:
        self._enroll.wait_for(min_devices, timeout)
        self.trainers, self.evaluator = self._enroll.assign_roles(
            want_evaluator=self.want_evaluator
        )
        for d in self.trainers + ([self.evaluator] if self.evaluator else []):
            self._clients[d.device_id] = TensorClient(
                d.host, d.port, timeout=protocol.CONNECT_TIMEOUT,
                ident=d.device_id)

    def close(self) -> None:
        self._stop.set()
        with self._version_cv:
            # Wake pumps parked on the version condition — shutdown must
            # not depend on their poll timeout.
            self._version_cv.notify_all()
        for t in self._threads:
            t.join(timeout=2 * self.request_timeout)
        for t in self._drainers:
            t.join(timeout=2 * self.agg_interval_s + 2.0)
        for c in self._clients.values():
            c.close()
        with self._agg_lock:
            if self._agg_sub is not None:
                self._agg_sub.close()
                self._agg_sub = None
        self._broker.close()
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None
        if self.health is not None:
            with self._health_lock:
                self.health.flush()
                self.health.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def _snapshot(self):
        """(version, params-as-numpy, encoded frame) under the state lock —
        dispatchers must never read params mid-server-update.  The frame is
        encoded once per model VERSION and shared read-only by every pump
        (``comm.broadcast_encode_total``), instead of once per dispatch."""
        from colearn_federated_learning_tpu.comm.downlink import host_params
        from colearn_federated_learning_tpu.utils.serialization import (
            pytree_to_bytes,
        )

        with self._state_lock:
            v = self.version
            if self._snap_cache is None or self._snap_cache[0] != v:
                # host_params reads sharded leaves PER SHARD (the PR 9
                # gather-free path) and is a plain asarray when the
                # server runs replicated.
                params_np = host_params(self.server_state.params)
                body = memoryview(pytree_to_bytes(params_np, {"round": v}))
                telemetry.get_registry().counter(
                    "comm.broadcast_encode_total").inc()
                self._snap_cache = (v, params_np, body)
            return self._snap_cache

    def _dispatch_loop(self, dev: DeviceInfo) -> None:
        """One device's pump: train on the freshest model, enqueue, repeat.

        At most ONE training run per (device, model version): a worker's
        local update is deterministic per version (per-(client, round) PRNG
        keys), so re-dispatching the same version would enqueue byte-equal
        duplicates — a fast device could then dominate the buffer with
        copies of one update while slower peers compile.  The pump blocks
        on the version condition until the aggregator advances."""
        cli = self._clients[dev.device_id]
        last_v = -1
        while not self._stop.is_set():
            self._pump_state[dev.device_id] = "wait"
            with self._version_cv:
                while self.version == last_v and not self._stop.is_set():
                    # The timeout is a belt-and-braces poll, NOT the wake
                    # mechanism: the aggregator notifies under the cv it
                    # holds across the version increment, and close()
                    # notifies after setting the stop event — tests pin
                    # liveness with this poll inflated to minutes.
                    self._version_cv.wait(self._cv_poll_s)
            if self._stop.is_set():
                return
            if dev.device_id in self._pruned:
                # Paused pump (straggler pruning): a pruned device is a
                # predicted dropout — dispatching would burn its compute
                # on an update destined for the staleness discard.  Idle
                # on the stop event until probation re-admits it.
                self._pump_state[dev.device_id] = "pruned"
                self._stop.wait(0.25)
                continue
            v, _params_np, body = self._snapshot()
            self._pump_state[dev.device_id] = "train"
            t_req = time.perf_counter()
            try:
                with self.tracer.span("dispatch_train",
                                      device=dev.device_id,
                                      version=v) as dispatch_sp:
                    header, delta = cli.request(
                        protocol.attach_trace(
                            {"op": "train", "round": v},
                            self.tracer.current_context(),
                        ),
                        body=body, timeout=self.request_timeout,
                    )
                if header.get("status") != "ok":
                    raise RuntimeError(header.get("error"))
                protocol.pop_trace_spans(header.get("meta"), self.tracer)
            except Exception:
                if self._stop.is_set():
                    return
                self._pump_state[dev.device_id] = "retry"
                self.failures[dev.device_id] = (
                    self.failures.get(dev.device_id, 0) + 1
                )
                telemetry.get_registry().counter(
                    "async.dispatch_failures").inc()
                self._record_health(dev.device_id, retry=1)
                streak = self._fail_streak.get(dev.device_id, 0) + 1
                self._fail_streak[dev.device_id] = streak
                if streak >= self.evict_after:
                    # Dead-pump eviction: retrying a permanently-dead
                    # peer every backoff forever wastes a thread and
                    # keeps it counted as an enrolled trainer.  Revoke
                    # and stop; elastic re-enrollment restarts the pump.
                    self._evict(dev)
                    return
                # Replace the connection (a late reply on the old socket
                # would desynchronise the request/reply stream), back off,
                # and RETRY the same version — last_v only advances on
                # success, so a flaky device can't starve an aggregation
                # that still needs its update.
                try:
                    cli.close()
                    cli = TensorClient(dev.host, dev.port,
                                       timeout=protocol.CONNECT_TIMEOUT,
                                       ident=dev.device_id)
                    self._clients[dev.device_id] = cli
                except OSError:
                    telemetry.get_registry().counter(
                        "comm.reconnect_failures_total").inc()
                self._stop.wait(0.2)
                continue
            self._fail_streak.pop(dev.device_id, None)
            lat = time.perf_counter() - t_req
            self._record_health(dev.device_id, round=v, latency_s=lat)
            if lat > 0.5 * self.request_timeout:
                # Pump stall: the device answered, but burned most of the
                # dispatch timeout budget — the leading indicator the
                # health plane wants before the retry/eviction symptoms.
                telemetry.get_registry().counter(
                    "async.pump_stalls_total",
                    labels={"device": str(dev.device_id)}).inc()
                self._record_health(dev.device_id, pump_stall=1)
            self.arrival.observe(dev.device_id, now=time.monotonic())
            last_v = v
            if self.tree_mode:
                # Tree mode: the contribution streams to its assigned
                # aggregator's per-slice buffer under a per-contribution
                # dedup key; it stays in _inflight until a drained
                # partial acknowledges it (re-home coverage).
                self._tree_submit(dev.device_id, header["meta"], delta, v)
                continue
            # The update travels with its dispatch span context (version
            # lineage) and its arrival time (buffer-wait attribution).
            self._results.put((dev.device_id, header["meta"], delta, v,
                               dispatch_sp.context, time.perf_counter()))

    def _record_health(self, device_id: str, **kw) -> None:
        """Thread-safe ledger append (pumps + aggregator share it)."""
        if self.health is None:
            return
        with self._health_lock:
            self.health.record(str(device_id), **kw)

    def _evict(self, dev: DeviceInfo) -> None:
        """Revoke a trainer whose pump hit ``evict_after`` consecutive
        dispatch failures.  Runs ON the dying pump thread; the thread
        renames itself so a later elastic re-admission of the same
        device can start a fresh pump under the canonical name."""
        with self._state_lock:
            self.trainers = [t for t in self.trainers
                             if t.device_id != dev.device_id]
            self.evicted.append(dev.device_id)
            self._evicted_pending.append(dev.device_id)
        cli = self._clients.pop(dev.device_id, None)
        if cli is not None:
            cli.close()
        self._fail_streak.pop(dev.device_id, None)
        self._pump_state[dev.device_id] = "evicted"
        telemetry.get_registry().counter("fed.devices_evicted_total").inc()
        self._record_health(dev.device_id, eviction=1)
        threading.current_thread().name = (
            f"dispatch-{dev.device_id}-evicted")

    def _update_pruning(self, agg_idx: int) -> None:
        """Once per aggregation: probation re-admission, then pruning.

        Re-admission runs first — a device whose probation window ended
        gets its pump back (with a clean streak) before this
        aggregation's candidates are scored.  Candidates come from two
        triggers: ``prune_after`` consecutive too-stale discards
        (reason="stale"), and a health-ledger score at or above
        ``prune_score`` (reason="score"), where the score is the
        ledger's weighted failure count plus a latency term — how far
        the device's latency EWMA sits above the fleet median, in
        multiples (CLIP's predicted-dropout signal without a second
        threshold).  Pruning never shrinks the active pump set below
        ``buffer_size``: the buffer must stay fillable."""
        reg = telemetry.get_registry()
        for d in [d for d, until in self._pruned.items()
                  if until <= agg_idx]:
            del self._pruned[d]
            self._stale_streak.pop(d, None)
            reg.counter("async.devices_readmitted_total").inc()
        candidates: list[tuple[float, str, str]] = []
        if self.prune_after:
            for d, streak in self._stale_streak.items():
                if streak >= self.prune_after and d not in self._pruned:
                    candidates.append((float(streak), d, "stale"))
        if self.prune_score:
            with self._health_lock:
                fleet = self.health.devices()
            ewmas = [h.lat_ewma for h in fleet.values()
                     if h.lat_ewma is not None]
            median = float(np.median(ewmas)) if ewmas else 0.0
            flagged = {d for _, d, _ in candidates}
            for d, h in fleet.items():
                if d in self._pruned or d in flagged:
                    continue
                eff = h.score()
                if median > 0 and h.lat_ewma is not None:
                    eff += max(0.0, h.lat_ewma / median - 1.0)
                if eff >= self.prune_score:
                    candidates.append((eff, d, "score"))
        if not candidates:
            return
        # Worst offenders first; stop the moment one more pause would
        # leave fewer active pumps than the buffer needs.
        candidates.sort(key=lambda c: (-c[0], c[1]))
        with self._state_lock:
            enrolled = {t.device_id for t in self.trainers}
        for _, d, reason in candidates:
            if d not in enrolled:
                continue
            active = len(enrolled) - len(self._pruned)
            if active - 1 < self.buffer_size:
                break
            self._pruned[d] = agg_idx + self.probation
            reg.counter("async.devices_pruned_total",
                        labels={"reason": reason}).inc()
            # Attribute the prune to the device in the health ledger
            # (CLIP's predicted dropout IS a health event).
            if self.health is not None:
                with self._health_lock:
                    self.health.record(str(d), prune=1)

    def _health_async_feed(self) -> dict:
        """Per-aggregation ledger flush + merged fleet view (the sync
        coordinator's ``_health_round_feed``, async flavor).  The pumps
        already attributed latency/retry/eviction and the collect loop
        attributed deadline misses, so this only folds the transport's
        per-device retry deltas, flushes durably, and reloads the
        directory (merged across any co-located writers)."""
        from colearn_federated_learning_tpu.telemetry import health as _hl

        with self._health_lock:
            _hl.feed_transport_retries(self.health,
                                       self._health_retry_seen)
            self.health.flush()
            fleet = _hl.load_health(os.path.dirname(self.health.path))
        _hl.export_gauges(fleet)
        return fleet

    def _start_dispatchers(self) -> None:
        # Dead pumps (evicted devices) drop out of the dedupe set so a
        # re-enrolled device gets a fresh pump under the same name.
        self._threads = [t for t in self._threads if t.is_alive()]
        started = {t.name for t in self._threads}
        with self._state_lock:
            roster = list(self.trainers)
        for d in roster:
            name = f"dispatch-{d.device_id}"
            if name in started:
                continue
            t = threading.Thread(target=self._dispatch_loop, args=(d,),
                                 daemon=True, name=name)
            t.start()
            self._threads.append(t)

    def refresh_membership(self, poll: float = 0.1) -> list[str]:
        """Elastic late-join, async flavor: devices that enrolled after
        ``enroll()`` get the trainer role and their own dispatch pump —
        they start contributing to the NEXT aggregations immediately
        (the sync coordinator's equivalent admits per round)."""
        from colearn_federated_learning_tpu.comm.enrollment import (
            admit_late_joiners,
        )

        if not self._broker.alive():
            # Control-plane SPOF healed in place, async flavor: a
            # SIGKILLed-and-restarted broker loses our enrollment
            # subscription; the fresh manager's retained-topic replay
            # re-admits the fleet (pumps keep dispatching the whole
            # time — training rides direct tensor connections).
            self._rebuild_broker()
        try:
            admitted = admit_late_joiners(self._enroll, self._broker,
                                          self.trainers, self.evaluator,
                                          self._clients, poll)
        except (OSError, protocol.ConnectionClosed):
            # Broker died between the liveness check and the poll (a
            # SIGKILL mid-recv surfaces as ConnectionClosed — the
            # tree-async soak kills exactly this window).
            self._rebuild_broker()
            return []
        if admitted and self._threads:
            self._start_dispatchers()      # pumps for the newcomers only
        if admitted and self.tree_mode:
            with self._agg_lock:
                self._recompute_assignment()
        return admitted

    def _rebuild_broker(self) -> None:
        """Reconnect the control plane after a broker death.
        Aggregations keep running either way (contributions ride direct
        tensor connections; only membership refresh and the aggregator
        announce topic need the broker) — the outcome is counted, never
        silent, and ``_refresh_aggs`` heals its own subscription on its
        next call."""
        reg = telemetry.get_registry()
        try:
            fresh = BrokerClient(self._broker_addr[0], self._broker_addr[1],
                                 timeout=protocol.CONNECT_TIMEOUT)
        except OSError:
            reg.counter("comm.broker_reconnects_total",
                        labels={"outcome": "failed"}).inc()
            return
        self._broker.close()
        self._broker = fresh
        self._enroll = EnrollmentManager(fresh, mud_policy=self._mud_policy)
        reg.counter("comm.broker_reconnects_total",
                    labels={"outcome": "ok"}).inc()

    # ---- aggregator tree (tree-async mode) ---------------------------
    def enroll_aggregators(self, n: Optional[int] = None,
                           timeout: float = 30.0) -> list[int]:
        """Discover ``n`` aggregators from their retained announce
        records, mark them live, and start one drainer thread per
        aggregator slot.  Call after :meth:`enroll` (slice assignment
        needs the trainer roster)."""
        from colearn_federated_learning_tpu.comm import aggregator as agg_lib

        n = self.num_aggregators if n is None else int(n)
        deadline = time.monotonic() + timeout
        while True:
            self._refresh_aggs(drain_timeout=0.2)
            with self._agg_lock:
                found = len(self._aggs)
            if found >= n:
                break
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"only {found}/{n} aggregators announced within "
                    f"{timeout:.0f}s")
        with self._agg_lock:
            ids = sorted(self._aggs)
            self._recompute_assignment()
        for aid in ids:
            t = threading.Thread(target=self._drain_loop, args=(aid,),
                                 daemon=True, name=f"agg-drain-{aid}")
            t.start()
            self._drainers.append(t)
        return ids

    def _refresh_aggs(self, drain_timeout: float = 0.02) -> None:
        """Drain the retained announce topic into ``_aggs`` (latest
        record per agg_id wins — a restarted aggregator overwrites its
        dead predecessor's address).  Heals the subscription in place
        when the broker itself was restarted.

        All broker I/O happens OUTSIDE ``_agg_lock`` (CL019): the
        subscription is serialized by a non-blocking try-acquire on the
        dedicated ``_agg_refreshing`` gate — a contending caller returns
        immediately and rides on the in-flight refresh (every caller is
        a retry loop, so a ~drain_timeout-stale heartbeat view heals on
        its next pass) — and announce records drain into a local dict
        that is merged under ``_agg_lock`` at the end."""
        from colearn_federated_learning_tpu.comm import aggregator as agg_lib

        if not self._agg_refreshing.acquire(blocking=False):
            return
        try:
            with self._agg_lock:
                sub = self._agg_sub
            if sub is None:
                try:
                    sub = BrokerClient(self._broker_addr[0],
                                       self._broker_addr[1],
                                       timeout=protocol.CONNECT_TIMEOUT)
                    sub.subscribe(agg_lib.AGG_TOPIC + "#")
                except OSError:
                    telemetry.get_registry().counter(
                        "comm.broker_reconnects_total",
                        labels={"outcome": "failed"}).inc()
                    return
                with self._agg_lock:
                    self._agg_sub = sub
            fresh: dict = {}
            try:
                agg_lib.fetch_aggregators(sub, fresh,
                                          drain_timeout=drain_timeout)
            except (protocol.ConnectionClosed, OSError):
                with self._agg_lock:
                    if self._agg_sub is sub:
                        self._agg_sub = None  # broker died; rebuilt next call
                try:
                    sub.close()
                except OSError:
                    protocol.count_suppressed()  # already torn down
                return
            if fresh:
                with self._agg_lock:
                    self._aggs.update(fresh)
        finally:
            self._agg_refreshing.release()

    def _live_agg_ids(self) -> list[int]:
        with self._agg_lock:
            return sorted(a for a in self._aggs if a not in self._dead_aggs)

    def _recompute_assignment(self) -> None:  # colearn: holds(_agg_lock)
        """Device → aggregator map over the LIVE aggregators, health-
        driven when a ledger is attached (chronic stragglers concentrate
        in the last — deepest-buffer — slices).  Caller holds
        ``_agg_lock``."""
        from colearn_federated_learning_tpu.comm import aggregator as agg_lib

        live = sorted(a for a in self._aggs if a not in self._dead_aggs)
        if not live:
            self._assign = {}
            return
        with self._state_lock:
            roster = list(self.trainers)
        ids = sorted((t.device_id for t in roster), key=str)
        scores = None
        if self.health is not None:
            with self._health_lock:
                fleet = self.health.devices()
            if fleet:
                scores = {str(d): h.score() for d, h in fleet.items()}
        slices = agg_lib.assign_slices(ids, len(live), scores=scores)
        assign: dict[str, int] = {}
        reg = telemetry.get_registry()
        for aid, sl in zip(live, slices):
            for d in sl:
                assign[d] = aid
            reg.gauge("comm.agg_slice_devices",
                      labels={"agg": str(aid)}).set(float(len(sl)))
        self._assign = assign

    def _slice_size(self, aid: int) -> int:
        with self._agg_lock:
            return sum(1 for a in self._assign.values() if a == aid)

    def _agg_failure(self, aid: int) -> None:
        """One failed aggregator RPC: refresh the heartbeat view and
        declare the aggregator dead only past the bounded detection
        deadline (a transient hiccup on a live process is retried)."""
        self._refresh_aggs()
        now = time.time()
        rehome_keys: list = []
        with self._agg_lock:
            info = self._aggs.get(aid)
            if info is None or aid in self._dead_aggs:
                return
            age = now - float(info.get("ts", 0.0))
            telemetry.get_registry().gauge(
                "comm.agg_heartbeat_age_s",
                labels={"agg": str(aid)}).set(age)
            if age <= self.config.run.agg_heartbeat_timeout:
                return
            # Dead: sticky by ADDRESS — this process's buffer is gone
            # and must never be drained again; a restart announces a
            # fresh (host, port) and re-admits the slot.
            self._dead_aggs.add(aid)
            self._dead_addrs.add((str(info["host"]), int(info["port"])))
            telemetry.get_registry().counter(
                "comm.agg_heartbeat_expired_total").inc()
            self._recompute_assignment()
            with self._inflight_lock:
                rehome_keys = [k for k, ent in self._inflight.items()
                               if ent[4] == aid]
        # Re-home OUTSIDE the locks: every contribution still in flight
        # at the dead aggregator is re-sent to a live sibling under its
        # original dedup key (idempotent staging at the receiver), and
        # the device is attributed in the health ledger.
        for key in rehome_keys:
            with self._inflight_lock:
                ent = self._inflight.get(key)
            if ent is None or ent[4] != aid:
                continue            # drained or already re-homed
            dev_id, meta, delta, v, _ = ent
            telemetry.get_registry().counter(
                "comm.agg_failovers_total",
                labels={"action": "rehome"}).inc()
            telemetry.get_registry().counter(
                "comm.agg_rehomed_total").inc()
            with self._inflight_lock:
                self._failovers_pending += 1
                self._rehomed_total += 1
                self._rehomed_pending.add(str(dev_id))
            self._record_health(dev_id, rehomed=1)
            self._send_contribution(key, dev_id, meta, delta, v,
                                    rehomed=True)

    def _maybe_resurrect(self, aid: int) -> bool:
        """Re-admit a dead aggregator slot once a FRESH announce (an
        address never declared dead) appears — the restarted process has
        an empty buffer, so re-admission cannot double-fold."""
        with self._agg_lock:
            if aid not in self._dead_aggs:
                return True
            info = self._aggs.get(aid)
            if not info:
                return False
            addr = (str(info["host"]), int(info["port"]))
            if addr in self._dead_addrs:
                return False
            self._dead_aggs.discard(aid)
            self._recompute_assignment()
            return True

    def _tree_submit(self, dev_id: str, meta: dict, delta, v: int,
                     rehomed: bool = False) -> None:
        key = f"{int(v):08d}@{dev_id}"
        with self._inflight_lock:
            self._inflight[key] = (str(dev_id), dict(meta), delta,
                                   int(v), None)
        self._send_contribution(key, dev_id, meta, delta, v,
                                rehomed=rehomed)

    def _send_contribution(self, key: str, dev_id: str, meta: dict,
                           delta, v: int, rehomed: bool = False) -> bool:
        """Stream one contribution into an aggregator buffer: the
        assigned aggregator first, then live siblings.  The accepting
        aggregator is recorded on the in-flight entry (that is the
        buffer a later failover re-homes FROM).  Blocks — bounded by the
        stop event — while no aggregator is reachable; contributions are
        never dropped at this seam.

        A contribution whose HOME aggregator (the slice assignment at
        call entry) fails mid-flight and that lands on a sibling instead
        is a re-home too — it carries the ``rehomed`` wire flag and the
        device is attributed in the health ledger, exactly like the
        explicit buffer re-home after a death."""
        home: Optional[int] = None
        home_failed = False
        while not self._stop.is_set():
            with self._agg_lock:
                assigned = self._assign.get(str(dev_id))
                live = [a for a in sorted(self._aggs)
                        if a not in self._dead_aggs]
                infos = {a: dict(self._aggs[a]) for a in live}
            if home is None:
                home = assigned
            order = ([assigned] if assigned in live else []) + [
                a for a in live if a != assigned]
            for aid in order:
                info = infos[aid]
                fallback = home_failed and aid != home
                cli = None
                try:
                    # Short-lived connection per contribution: the pumps
                    # stream concurrently and the tensor transport is a
                    # strict request/reply stream per socket.
                    cli = TensorClient(info["host"], int(info["port"]),
                                       timeout=protocol.CONNECT_TIMEOUT,
                                       ident=str(dev_id))
                    hdr, _ = cli.request(
                        {"op": "abuf", "key": key, "device": str(dev_id),
                         "version": int(v),
                         "rehomed": bool(rehomed or fallback),
                         "meta": dict(meta)},
                        delta, timeout=self.request_timeout)
                    if hdr.get("status") != "ok":
                        raise RuntimeError(hdr.get("error"))
                    with self._inflight_lock:
                        if key in self._inflight:
                            ent = self._inflight[key]
                            self._inflight[key] = ent[:4] + (aid,)
                    if fallback and not rehomed:
                        # Pump-side failover: the explicit path already
                        # attributed before calling, this one hasn't.
                        reg = telemetry.get_registry()
                        reg.counter("comm.agg_failovers_total",
                                    labels={"action": "rehome"}).inc()
                        reg.counter("comm.agg_rehomed_total").inc()
                        with self._inflight_lock:
                            self._failovers_pending += 1
                            self._rehomed_total += 1
                            self._rehomed_pending.add(str(dev_id))
                        self._record_health(dev_id, rehomed=1)
                    return True
                except Exception:
                    if self._stop.is_set():
                        return False
                    if aid == home:
                        home_failed = True
                    self._agg_failure(aid)
                    continue
                finally:
                    if cli is not None:
                        cli.close()
            self._stop.wait(0.2)    # nobody live: wait for a restart
        return False

    def _drain_loop(self, aid: int) -> None:
        """One aggregator slot's drainer: long-poll ``adrain`` for the
        next partial fold.  A drained partial's keys are acknowledged
        (removed from ``_inflight``) IMMEDIATELY on receipt — once the
        partial is in root memory those contributions are no longer
        in-flight, so a subsequent aggregator death cannot re-home (and
        double-fold) them."""
        cli: Optional[TensorClient] = None
        poll = max(self.agg_interval_s, 0.5)
        while not self._stop.is_set():
            if not self._maybe_resurrect(aid):
                self._refresh_aggs()
                if cli is not None:
                    cli.close()
                    cli = None
                self._stop.wait(0.25)
                continue
            with self._agg_lock:
                info = dict(self._aggs.get(aid) or {})
            if not info:
                self._refresh_aggs()
                self._stop.wait(0.25)
                continue
            if cli is None:
                try:
                    cli = TensorClient(info["host"], int(info["port"]),
                                       timeout=protocol.CONNECT_TIMEOUT,
                                       ident=f"agg:{aid}")
                    hdr, _ = cli.request({"op": "aprep", "meta": {}},
                                         self._shapes_np,
                                         timeout=self.request_timeout)
                    if hdr.get("status") != "ok":
                        raise RuntimeError(hdr.get("error"))
                except Exception:
                    if self._stop.is_set():
                        return
                    if cli is not None:
                        cli.close()
                        cli = None
                    self._agg_failure(aid)
                    self._stop.wait(0.25)
                    continue
            try:
                hdr, tree = cli.request(
                    {"op": "adrain", "interval_s": self.agg_interval_s,
                     "timeout": poll,
                     "slice_devices": self._slice_size(aid)},
                    timeout=poll + self.request_timeout)
                if hdr.get("status") != "ok":
                    raise RuntimeError(hdr.get("error"))
                meta = hdr.get("meta") or {}
                if not int(meta.get("count", 0)):
                    continue                      # idle poll
                with self._inflight_lock:
                    for k in meta.get("keys", []):
                        self._inflight.pop(k, None)
                self._partials.put((meta, tree, time.perf_counter()))
            except Exception:
                if self._stop.is_set():
                    return
                cli.close()
                cli = None
                self._agg_failure(aid)
                self._stop.wait(0.1)

    # ------------------------------------------------------------------
    def run_aggregation(self) -> dict:
        """Block until ``buffer_size`` fresh-enough updates arrived, then
        apply the staleness-weighted mean as one server step.  Raises
        RuntimeError (with per-device failure counts) if the federation
        produces nothing for ``2 × request_timeout`` — dispatchers retry
        dead peers forever, so the aggregator owns the escalation."""
        from colearn_federated_learning_tpu.comm.aggregation import (
            StreamingFolder,
        )

        reg = telemetry.get_registry()
        if self.tree_mode:
            return self._run_tree_aggregation()
        if self.auto_buffer:
            # Adaptive K — the telemetry made load-bearing: size the
            # buffer so a fold lands about every auto_interval_s at the
            # observed fleet arrival rate, clamped to [1, trainers]
            # (each device contributes at most one update per version,
            # so a larger buffer could never fill).
            seen = self._folded_total + self._discarded_total
            fold_frac = self._folded_total / seen if seen else 1.0
            k = self.arrival.recommend_buffer(
                self.auto_interval_s * max(fold_frac, 0.05), lo=1,
                hi=max(1, len(self.trainers)), current=self.buffer_size)
            # Slew-limit the resize: the rate estimate trails load
            # swings by one buffer fill, so jumping straight to the
            # recommendation overshoots the cadence band it chases.
            k = max(max(1, self.buffer_size // 2),
                    min(k, max(2, self.buffer_size * 3 // 2)))
            if k != self.buffer_size:
                reg.counter("async.buffer_resizes_total").inc()
                self.buffer_size = k
        if self.buffer_size > len(self.trainers):
            raise ValueError(
                f"buffer_size {self.buffer_size} exceeds the "
                f"{len(self.trainers)} enrolled trainers: each device "
                "contributes at most one update per model version, so the "
                "buffer could never fill"
            )
        self._start_dispatchers()
        reg.gauge("async.buffer_target").set(float(self.buffer_size))
        t0 = time.perf_counter()
        # StreamingFolder (the uplink fast path + sharded server): topk
        # replies stage their wire (indices, values) sparse — O(k) per
        # update — and under a tp placement every contribution folds
        # shard-wise.  Staging keys are ARRIVAL-ORDERED (a device can
        # land updates for two versions in one buffer, so the bare
        # client_id would collide), and the zero-padded arrival index
        # makes the folder's sorted finalize reproduce the arrival-order
        # sum the dense UpdateFolder used to compute — bitwise.
        folder = StreamingFolder(self._shapes_np,
                                 placement=self._placement,
                                 device_fold=self._fold_device)
        staleness: list[int] = []
        contributors: list[str] = []
        weights: list[float] = []
        discarded = 0
        mass_folded = 0.0
        mass_discarded = 0.0
        fold_span_ids: list[str] = []
        stall_deadline = t0 + 2.0 * self.request_timeout
        # The async.aggregate span owns this aggregation's timeline; each
        # consumed update additionally records a fold_update span PARENTED
        # on its dispatch context — version lineage: the span joins that
        # update's dispatch→train trace, carrying τ, outcome, and
        # buffer-wait — and is cross-linked to this span by id
        # (link_agg / link_folds, the PR 12 tree-stitch flow pattern).
        with self.tracer.span("async.aggregate", version=self.version,
                              buffer_size=self.buffer_size) as agg_sp:
            with self.tracer.span(
                    "collect_updates",
                    buffer_size=self.buffer_size) as collect_sp:
                while len(staleness) < self.buffer_size:
                    try:
                        dev_id, meta, delta, v, dctx, t_arr = (
                            self._results.get(timeout=max(
                                0.1,
                                stall_deadline - time.perf_counter()))
                        )
                    except queue.Empty:
                        raise RuntimeError(
                            f"no update arrived within "
                            f"{2 * self.request_timeout:.0f}s "
                            f"({len(staleness)}/{self.buffer_size} "
                            f"buffered); "
                            f"device failures: {dict(self.failures)}"
                        ) from None
                    stall_deadline = (time.perf_counter()
                                      + 2.0 * self.request_timeout)
                    tau = self.version - v
                    stale_w = (1.0 + tau) ** (-self.staleness_exponent)
                    wait_s = time.perf_counter() - t_arr
                    if tau > self.max_staleness:
                        # Per-device attribution: the labeled child rolls
                        # up into the unlabeled family, so aggregate
                        # readers (soak deltas) keep working.
                        discarded += 1
                        self._discarded_total += 1
                        mass_discarded += stale_w
                        reg.counter("async.updates_discarded_stale",
                                    labels={"device": str(dev_id)}).inc()
                        reg.counter(
                            "async.contribution_mass",
                            labels={"outcome": "discarded"}).inc(stale_w)
                        reg.histogram(
                            "async.staleness",
                            labels={"outcome": "discarded"}).observe(
                                float(tau))
                        with self.tracer.span(
                                "fold_update", parent=dctx,
                                device=str(dev_id), tau=tau, version=v,
                                applied_version=self.version,
                                outcome="discarded",
                                buffer_wait_s=wait_s,
                                link_agg=agg_sp.span_id):
                            pass
                        self._stale_streak[dev_id] = (
                            self._stale_streak.get(dev_id, 0) + 1)
                        self._record_health(dev_id, round=self.version,
                                            deadline_miss=1)
                        continue
                    self._stale_streak.pop(dev_id, None)
                    w = float(meta.get("weight", 1.0)) * stale_w
                    fmeta = dict(meta)
                    fmeta["client_id"] = f"{len(staleness):08d}@{dev_id}"
                    with self.tracer.span(
                            "fold_update", parent=dctx,
                            device=str(dev_id), tau=tau, version=v,
                            applied_version=self.version,
                            outcome="folded", buffer_wait_s=wait_s,
                            link_agg=agg_sp.span_id) as fold_sp:
                        folder.add(fmeta, delta, weight=w)
                    fold_span_ids.append(fold_sp.span_id)
                    self._folded_total += 1
                    mass_folded += stale_w
                    reg.counter("async.contribution_mass",
                                labels={"outcome": "folded"}).inc(stale_w)
                    reg.histogram(
                        "async.staleness",
                        labels={"outcome": "folded"}).observe(float(tau))
                    staleness.append(tau)
                    contributors.append(dev_id)
                    weights.append(w)
                    reg.gauge("async.buffer_occupancy").set(
                        float(len(staleness)))

            with self.tracer.span("apply_update",
                                  version=self.version) as apply_sp:
                mean_delta, total_w, mean_loss = folder.mean()
                # Quorum over DISTINCT contributors (a slow federation
                # can fill the buffer with one device's updates across
                # versions).  A sub-quorum buffer is discarded — but the
                # version still advances, or every dispatcher pump would
                # block forever on a model that can never change.
                quorum = (max(1, math.ceil(self.min_cohort_fraction
                                           * len(self.trainers)))
                          if self.min_cohort_fraction > 0 else 0)
                skipped_quorum = (bool(quorum)
                                  and len(set(contributors)) < quorum)
                if skipped_quorum:
                    telemetry.get_registry().counter(
                        "fed.rounds_skipped_quorum").inc()
                    mean_delta = None
                    mean_loss = float("nan")
                with self._state_lock:
                    if mean_delta is not None:
                        self.server_state = strategies.server_update(
                            self.server_state, mean_delta, self.config.fed
                        )
                    # The version bump happens under BOTH locks:
                    # _state_lock keeps (server_state, version) consistent
                    # for _snapshot, and holding _version_cv across
                    # increment+notify closes the lost-wakeup window a
                    # pump would otherwise hit between reading version and
                    # calling wait() (today's 0.1 s poll would mask it,
                    # but the poll must not be load-bearing).
                    with self._version_cv:
                        self.version += 1
                        self._version_cv.notify_all()
                conv_sig = None
                if self._learn is not None:
                    # Aggregate-level learning signals; a discarded
                    # (sub-quorum) buffer observes nothing and leaves
                    # the trend state untouched.
                    conv_sig = self._learn.observe(
                        mean_delta, lr=self.config.fed.server_lr)
                    if conv_sig:
                        apply_sp.attrs["conv_update_norm"] = (
                            conv_sig["conv_update_norm"])
                        apply_sp.attrs["conv_trend"] = (
                            conv_sig["conv_trend"])
                        if "conv_cos_prev" in conv_sig:
                            apply_sp.attrs["conv_cos_prev"] = (
                                conv_sig["conv_cos_prev"])
                        self._learn.export_metrics(
                            telemetry.get_registry(), conv_sig)
            agg_sp.attrs["folded"] = len(staleness)
            agg_sp.attrs["discarded"] = discarded
            agg_sp.attrs["link_folds"] = fold_span_ids
        reg.gauge("async.buffer_occupancy").set(0.0)
        reg.gauge("async.pending_updates").set(float(self._results.qsize()))
        self._export_pump_gauges(reg)
        self.arrival.export_gauges(reg, "async.arrival_rate_per_s")
        agg_idx = len(self.history)
        reg.counter("async.aggregations_total").inc()
        # (Too-stale discards were already counted at the discard site —
        # the labeled per-device children roll up into the unlabeled
        # async.updates_discarded_stale family.)
        if self.prune_enabled:
            self._update_pruning(agg_idx)
        rec = {
            "aggregation": agg_idx,
            "model_version": self.version,
            "buffer_size": self.buffer_size,
            "staleness_mean": float(np.mean(staleness)),
            "staleness_max": int(np.max(staleness)),
            "discarded": discarded,
            "contributors": contributors,
            "train_loss": mean_loss,
            "total_weight": total_w,
            "agg_time_s": time.perf_counter() - t0,
            "phase_collect_s": collect_sp.duration_s,
            "phase_apply_s": apply_sp.duration_s,
        }
        if self.observe_records:
            # Observatory keys — only when observe/auto-K is on, so
            # default aggregation records stay byte-identical.
            rec["mass_folded"] = round(mass_folded, 6)
            rec["mass_discarded"] = round(mass_discarded, 6)
            rec["arrival_rate_per_s"] = round(self.arrival.rate(), 6)
            hs = reg.histogram("async.staleness",
                               labels={"outcome": "folded"}).summary()
            if hs.get("count"):
                rec["staleness_p50"] = hs["p50"]
                rec["staleness_p90"] = hs["p90"]
                rec["staleness_p99"] = hs["p99"]
        if quorum:
            # Key only present when the quorum feature is on, so default
            # aggregation records stay byte-identical.
            rec["skipped_quorum"] = skipped_quorum
        if self.prune_enabled:
            # Same convention: the pruning keys exist only when the
            # feature is on.
            rec["pruned"] = sorted(self._pruned)
        with self._state_lock:
            if self._evicted_pending:
                rec["evicted"] = self._evicted_pending
                self._evicted_pending = []
        reg.histogram("async.agg_time_s").observe(rec["agg_time_s"])
        if self.accountant is not None and mean_delta is not None:
            rec["dp_z_eff"] = self._charge_privacy(weights, contributors)
            rec["dp_epsilon"] = self.accountant.epsilon()
        if self.health is not None:
            fleet = self._health_async_feed()
            rec.update(telemetry.health_record_keys(fleet))
        if conv_sig:
            # conv_* learning-health keys only under --learn-observe —
            # default aggregation records stay byte-identical (pinned by
            # test).
            rec.update(conv_sig)
        self.history.append(rec)
        return rec

    def _run_tree_aggregation(self) -> dict:
        """Tree mode: consume ONE partial fold from the aggregator tier
        and apply it as one server step.

        Staleness is resolved AT THE ROOT against the partial's oldest
        constituent version: τ = version − oldest, the whole partial is
        scaled by ``(1+τ)^-staleness_exponent`` (conservative — no
        constituent is under-discounted), and a partial whose oldest
        constituent is past ``max_staleness`` is discarded outright with
        per-device attribution.  The version advances exactly once per
        applied (or sub-quorum-discarded) partial, same as the flat
        plane's per-buffer advance."""
        from colearn_federated_learning_tpu.comm.aggregation import (
            StreamingFolder,
        )
        from colearn_federated_learning_tpu.utils import pytrees

        reg = telemetry.get_registry()
        self._start_dispatchers()
        t0 = time.perf_counter()
        folder = StreamingFolder(self._shapes_np,
                                 placement=self._placement,
                                 device_fold=self._fold_device)
        discarded = 0
        mass_folded = 0.0
        mass_discarded = 0.0
        with self.tracer.span("async.aggregate", version=self.version,
                              tree=True) as agg_sp:
            with self.tracer.span("collect_updates") as collect_sp:
                stall_deadline = (time.perf_counter()
                                  + 2.0 * self.request_timeout)
                while True:
                    try:
                        meta, tree, _t_arr = self._partials.get(
                            timeout=max(0.1, stall_deadline
                                        - time.perf_counter()))
                    except queue.Empty:
                        raise RuntimeError(
                            f"no partial fold arrived within "
                            f"{2 * self.request_timeout:.0f}s; device "
                            f"failures: {dict(self.failures)}") from None
                    stall_deadline = (time.perf_counter()
                                      + 2.0 * self.request_timeout)
                    tau = max(0, self.version
                              - int(meta["oldest_version"]))
                    stale_w = (1.0 + tau) ** (-self.staleness_exponent)
                    n = int(meta["count"])
                    if tau > self.max_staleness:
                        # Whole-partial discard: the root's discount is
                        # pinned to the oldest constituent, so a partial
                        # it would zero out is dropped with per-device
                        # attribution (same streak/health feeds as the
                        # flat plane's per-update discard).
                        discarded += n
                        self._discarded_total += n
                        mass_discarded += stale_w * n
                        reg.counter(
                            "async.partials_discarded_stale").inc()
                        reg.counter(
                            "async.contribution_mass",
                            labels={"outcome": "discarded"}).inc(
                                stale_w * n)
                        reg.histogram(
                            "async.staleness",
                            labels={"outcome": "discarded"}).observe(
                                float(tau))
                        for d in meta["devices"]:
                            reg.counter(
                                "async.updates_discarded_stale",
                                labels={"device": str(d)}).inc()
                            self._stale_streak[str(d)] = (
                                self._stale_streak.get(str(d), 0) + 1)
                            self._record_health(str(d),
                                                round=self.version,
                                                deadline_miss=1)
                        continue
                    break
                contributors = [str(d) for d in meta["devices"]]
                staleness = [max(0, self.version - int(pv))
                             for pv in meta["versions"]]
                weights = [float(w) * stale_w for w in meta["weights"]]
                for d in contributors:
                    self._stale_streak.pop(d, None)
                scaled = None
                if tree is not None:
                    scaled = pytrees.tree_scale(
                        jax.tree.map(np.asarray, tree), stale_w)
                folder.add_partial(f"agg:{meta['agg_id']}",
                                   float(meta["total_w"]) * stale_w,
                                   scaled,
                                   float(meta["loss_sum"]) * stale_w,
                                   count=n)
                self._folded_total += n
                mass_folded += stale_w * n
                reg.counter("async.partials_folded_total",
                            labels={"agg": str(meta["agg_id"])}).inc()
                reg.counter("comm.agg_partials_folded_total",
                            labels={"agg": str(meta["agg_id"])}).inc()
                reg.counter("async.contribution_mass",
                            labels={"outcome": "folded"}).inc(
                                stale_w * n)
                for t_i in staleness:
                    reg.histogram(
                        "async.staleness",
                        labels={"outcome": "folded"}).observe(float(t_i))

            with self.tracer.span("apply_update",
                                  version=self.version) as apply_sp:
                mean_delta, total_w, mean_loss = folder.mean()
                quorum = (max(1, math.ceil(self.min_cohort_fraction
                                           * len(self.trainers)))
                          if self.min_cohort_fraction > 0 else 0)
                skipped_quorum = (bool(quorum)
                                  and len(set(contributors)) < quorum)
                if skipped_quorum:
                    reg.counter("fed.rounds_skipped_quorum").inc()
                    mean_delta = None
                    mean_loss = float("nan")
                with self._state_lock:
                    if mean_delta is not None:
                        self.server_state = strategies.server_update(
                            self.server_state, mean_delta,
                            self.config.fed)
                    with self._version_cv:
                        self.version += 1
                        self._version_cv.notify_all()
                conv_sig = None
                if self._learn is not None:
                    conv_sig = self._learn.observe(
                        mean_delta, lr=self.config.fed.server_lr)
                    if conv_sig:
                        self._learn.export_metrics(reg, conv_sig)
            agg_sp.attrs["folded"] = len(contributors)
            agg_sp.attrs["discarded"] = discarded
            agg_sp.attrs["agg_id"] = int(meta["agg_id"])
        reg.gauge("async.pending_updates").set(
            float(self._partials.qsize()))
        self._export_pump_gauges(reg)
        self.arrival.export_gauges(reg, "async.arrival_rate_per_s")
        agg_idx = len(self.history)
        reg.counter("async.aggregations_total").inc()
        if self.prune_enabled:
            self._update_pruning(agg_idx)
        with self._inflight_lock:
            failovers = self._failovers_pending
            self._failovers_pending = 0
            rehomed = sorted(self._rehomed_pending)
            self._rehomed_pending = set()
            rehomed_total = self._rehomed_total
        rec = {
            "aggregation": agg_idx,
            "model_version": self.version,
            "buffer_size": int(meta["buffer_k"]),
            "staleness_mean": float(np.mean(staleness)),
            "staleness_max": int(np.max(staleness)),
            "discarded": discarded,
            "contributors": contributors,
            "train_loss": mean_loss,
            "total_weight": total_w,
            "agg_time_s": time.perf_counter() - t0,
            "phase_collect_s": collect_sp.duration_s,
            "phase_apply_s": apply_sp.duration_s,
            # Tree-gated keys: present only in tree mode (itself
            # non-default), so default-config records on every plane
            # remain byte-identical.
            "agg_id": int(meta["agg_id"]),
            "agg_buffer_k": int(meta["buffer_k"]),
            "agg_buffer_rate_per_s": round(
                float(meta["arrival_rate_per_s"]), 6),
            "oldest_version": int(meta["oldest_version"]),
            "folded_keys": [str(k) for k in meta["keys"]],
            "agg_failovers": failovers,
            "rehomed_devices": rehomed,
            "rehomed_total": rehomed_total,
        }
        if self.observe_records:
            rec["mass_folded"] = round(mass_folded, 6)
            rec["mass_discarded"] = round(mass_discarded, 6)
            rec["arrival_rate_per_s"] = round(self.arrival.rate(), 6)
            hs = reg.histogram("async.staleness",
                               labels={"outcome": "folded"}).summary()
            if hs.get("count"):
                rec["staleness_p50"] = hs["p50"]
                rec["staleness_p90"] = hs["p90"]
                rec["staleness_p99"] = hs["p99"]
        if quorum:
            rec["skipped_quorum"] = skipped_quorum
        if self.prune_enabled:
            rec["pruned"] = sorted(self._pruned)
        with self._state_lock:
            if self._evicted_pending:
                rec["evicted"] = self._evicted_pending
                self._evicted_pending = []
        reg.histogram("async.agg_time_s").observe(rec["agg_time_s"])
        if self.accountant is not None and mean_delta is not None:
            rec["dp_z_eff"] = self._charge_privacy(weights, contributors)
            rec["dp_epsilon"] = self.accountant.epsilon()
        if self.health is not None:
            fleet = self._health_async_feed()
            rec.update(telemetry.health_record_keys(fleet))
        if conv_sig:
            rec.update(conv_sig)
        self.history.append(rec)
        return rec

    def _export_pump_gauges(self, reg) -> None:
        """Per-pump-state gauge children (``async.pumps{state=...}``):
        every known state is set each aggregation — including zeros — so
        a scrape always sees the full partition, not just states some
        pump happened to visit."""
        states: dict[str, int] = {}
        for st in list(self._pump_state.values()):
            states[st] = states.get(st, 0) + 1
        for st in ("wait", "train", "retry", "pruned", "evicted"):
            reg.gauge("async.pumps", labels={"state": st}).set(
                float(states.get(st, 0)))

    def _charge_privacy(self, weights: list[float],
                        contributors: list[str]) -> float:
        """Charge one APPLIED aggregation to the RDP accountant and return
        the realized effective noise multiplier.

        Mechanism per aggregation: each buffered update was clipped to
        ``C`` and carries independent per-update Gaussian noise of std
        ``s = σ·C/√B_cfg`` (setup.finalize_client_delta — B_cfg is the
        configured cohort), and the release is the weighted mean
        ``W⁻¹ Σ wᵢ dᵢ``:

        - central noise std: ``√(Σ wᵢ²)·s / W`` (noise is independent
          per update, including two updates from the same device at
          distinct versions — distinct dp_keys);
        - one DEVICE's worst-case influence: ``C · (Σ of ITS weights)/W``
          — a slow device can land updates for two versions in one
          buffer, so weights are grouped per device;
        - effective multiplier:
          ``z_eff = (σ/√B_cfg) · √(Σ wᵢ²) / max_dev(Σ w)``.

        RDP composes additively over aggregations, and charging EVERY
        applied aggregation upper-bounds each client's loss (an
        aggregation without a client costs that client nothing).
        DISCARDED (too-stale) updates are never released and charge
        nothing — the trusted-aggregator boundary of central DP.
        """
        import math

        c = self.config.fed
        b_cfg = setup_lib.dp_effective_cohort(self.config)
        per_dev: dict[str, float] = {}
        for w, d in zip(weights, contributors):
            per_dev[d] = per_dev.get(d, 0.0) + w
        warr = np.asarray(weights, np.float64)
        z_eff = (c.dp_noise_multiplier / math.sqrt(b_cfg)
                 * math.sqrt(float(np.sum(warr * warr)))
                 / max(per_dev.values()))
        self.accountant.step(1, sampling_rate=1.0, noise_multiplier=z_eff)
        return float(z_eff)

    def evaluate(self) -> dict:
        from colearn_federated_learning_tpu.comm.downlink import host_params

        if self.evaluator is None:
            raise RuntimeError("no evaluator was assigned")
        # Gather-free under a tp placement (per-shard host reads), a
        # plain asarray when the server runs replicated.
        params_np = host_params(self.server_state.params)
        with self.tracer.span("evaluate"):
            header, _ = self._clients[self.evaluator.device_id].request(
                protocol.attach_trace({"op": "eval"},
                                      self.tracer.current_context()),
                params_np, timeout=self.request_timeout,
            )
        if header.get("status") != "ok":
            raise RuntimeError(f"evaluator failed: {header.get('error')}")
        meta = header["meta"]
        protocol.pop_trace_spans(meta, self.tracer)
        return meta

    # ---- checkpoint/resume (same RoundCheckpointer as the engine, or the
    # shard-native StreamingCheckpointer when run.ckpt_stream is set) ------
    def _checkpointer(self):
        if self._ckpt is None:
            from colearn_federated_learning_tpu.ckpt import (
                RoundCheckpointer,
                StreamingCheckpointer,
            )

            cls = (StreamingCheckpointer if self.config.run.ckpt_stream
                   else RoundCheckpointer)
            self._ckpt = cls.for_run(self.config.run)
        return self._ckpt

    def save_checkpoint(self) -> None:
        self._checkpointer().save(
            self.version, (self.server_state,), self.history
        )

    def restore_checkpoint(self) -> int:
        """Restore the latest checkpoint; returns the resumed model
        version.  Call BEFORE ``enroll``/``fit`` — the dispatcher pumps
        snapshot the restored state on their first cycle."""
        state, history, step = self._checkpointer().restore(
            (self.server_state,)
        )
        (self.server_state,) = state
        if self._placement is not None:
            # Restored leaves may come back as host arrays; re-place
            # them on the server mesh so the resumed run keeps the
            # sharded fold/update/snapshot plane.
            s = self.server_state
            put = self._placement.shard
            self.server_state = type(s)(
                params=put(s.params),
                opt_m=put(s.opt_m) if s.opt_m is not None else None,
                opt_v=put(s.opt_v) if s.opt_v is not None else None,
                control=(put(s.control) if s.control is not None
                         else None),
                round_idx=s.round_idx,
            )
        self.history = history
        with self._state_lock:
            self.version = step
            self._snap_cache = None
        if self.accountant is not None:
            # The async mechanism varies per aggregation (realized z_eff
            # depends on the buffer's staleness weights), so the budget is
            # rebuilt by replaying each record's charged multiplier rather
            # than the engine's constant-mechanism ``steps`` shortcut.
            # Reset first so restore is idempotent (a retried restore, or
            # one on an instance that already aggregated, must not
            # double-charge the history).
            self.accountant.steps = 0
            for rec in history:
                if "dp_z_eff" in rec:
                    self.accountant.step(1, sampling_rate=1.0,
                                         noise_multiplier=rec["dp_z_eff"])
        telemetry.get_registry().counter("fed.rounds_resumed_total").inc()
        return step

    def fit(self, aggregations: int, log_fn=None,
            eval_every: Optional[int] = None,
            elastic: bool = False) -> list[dict]:
        eval_every = eval_every or self.config.run.eval_every
        run = self.config.run
        ckpt_every = max(0, run.checkpoint_every)
        want_ckpt = bool(run.checkpoint_dir)
        # rec["aggregation"] is a CUMULATIVE index (repeated fit() calls
        # continue the history), so the final-eval/-checkpoint marker is
        # relative to where this call started.
        last = len(self.history) + aggregations - 1
        for _ in range(aggregations):
            if elastic:
                self.refresh_membership()
            rec = self.run_aggregation()
            if self.evaluator is not None and (
                rec["aggregation"] % max(1, eval_every) == 0
                or rec["aggregation"] == last
            ):
                rec.update(self.evaluate())
            if log_fn is not None:
                log_fn(rec)
            if want_ckpt and (
                (ckpt_every and (rec["aggregation"] + 1) % ckpt_every == 0)
                or rec["aggregation"] == last
            ):
                self.save_checkpoint()
        return self.history
