"""Device enrollment and trainer/evaluator role selection.

Mirrors the reference's MQTT negotiation (SURVEY.md §1 "Enrollment /
discovery": devices announce identity + readiness on topics; the
coordinator subscribes, assigns **trainer** / **evaluator** roles) on the
in-tree broker:

  device  --pub-->  colearn/enroll/{device_id}  {device_id, host, port,
                                                 num_examples, dataset}
  coord   --pub-->  colearn/role/{device_id}    {role: trainer|evaluator,
                                                 retain: true}

Both sides publish RETAINED per-device topics, so ordering never races:
a coordinator that subscribes after devices announced replays their
enrollments, and a device that subscribes after selection replays its
role.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.comm.broker import BrokerClient

ENROLL_TOPIC = "colearn/enroll/"      # + device_id (retained)
ROLE_TOPIC = "colearn/role/"          # + device_id (retained)


class EnrollmentTimeout(TimeoutError):
    """No coordinator assigned this device a role within the enrollment
    window (RunConfig.worker_enroll_timeout for the CLI worker).  Distinct
    from a generic TimeoutError so callers can tell "nobody wanted me"
    from a slow peer mid-round."""


@dataclasses.dataclass(frozen=True)
class DeviceInfo:
    device_id: str
    host: str
    port: int                         # tensor-plane server (transport.py)
    num_examples: int = 0
    dataset: str = ""
    # Hex-encoded DH public key for wire-plane secure aggregation
    # (comm/keyexchange.py); empty when the worker runs without masking
    # or in shared_seed mode.
    pubkey: str = ""
    # RFC 8520 MUD profile JSON (comm/mud.py) — the CoLearn identity the
    # coordinator's MudPolicy gates enrollment on; empty = no profile.
    mud: str = ""

    def to_fields(self) -> dict:
        return dataclasses.asdict(self)


def announce(client: BrokerClient, info: DeviceInfo) -> None:
    """Device side: publish readiness (reference: publish on MQTT topic)."""
    client.publish(ENROLL_TOPIC + info.device_id, info.to_fields(),
                   retain=True)


def _parse_enroll(header: dict) -> DeviceInfo:
    return DeviceInfo(
        device_id=str(header["device_id"]),
        host=str(header["host"]),
        port=int(header["port"]),
        num_examples=int(header.get("num_examples", 0)),
        dataset=str(header.get("dataset", "")),
        pubkey=str(header.get("pubkey", "")),
        mud=str(header.get("mud", "")),
    )


def fetch_device_info(client: BrokerClient, device_id: str,
                      timeout: float = 10.0,
                      cache: Optional[dict] = None) -> DeviceInfo:
    """Read one device's CURRENT retained enrollment record — how a
    worker looks up a PEER's DH public key for wire-plane secure
    aggregation.

    Subscribes with ``ack`` and reads until the broker's ``suback``
    arrives: everything queued BEFORE it (stale leftovers from earlier
    rounds, live re-announce pushes) is parsed but superseded by later
    records, so the returned record is the one the broker retained at
    subscribe time — a peer that re-enrolled with a fresh key can never
    be read one-restart behind.  Every enrollment record seen is stored
    into ``cache`` (a ``{device_id: DeviceInfo}`` dict the caller keeps
    across calls), so records for other subscribed peers are never
    consumed-and-lost.
    """
    if cache is not None and device_id in cache:
        return cache[device_id]
    topic = ENROLL_TOPIC + device_id
    client.subscribe(topic, ack=True)
    deadline = time.monotonic() + timeout
    found = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(f"no enrollment record for {device_id!r}")
        header, _ = client.recv(timeout=remaining)
        if header.get("op") == "suback" and header.get("topic") == topic:
            if found is not None:
                return found
            raise TimeoutError(
                f"device {device_id!r} has no retained enrollment record"
            )
        if not str(header.get("topic", "")).startswith(ENROLL_TOPIC):
            continue
        info = _parse_enroll(header)
        if cache is not None:
            cache[info.device_id] = info
        if info.device_id == device_id:
            found = info             # keep reading: latest wins


def await_role(client: BrokerClient, device_id: str,
               timeout: Optional[float] = None) -> str:
    """Device side: block until the coordinator assigns this device a role.
    Subscribe BEFORE announcing to avoid a race; retained messages cover
    the reverse order too."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
            raise EnrollmentTimeout(
                f"device {device_id} received no role assignment within "
                f"{timeout:.0f}s — is a coordinator running against this "
                "broker, and does its enrollment policy admit this device?"
            )
        try:
            header, _ = client.recv(timeout=remaining)
        except TimeoutError:
            raise EnrollmentTimeout(
                f"device {device_id} received no role assignment within "
                f"{timeout:.0f}s — is a coordinator running against this "
                "broker, and does its enrollment policy admit this device?"
            ) from None
        if header.get("topic") == ROLE_TOPIC + device_id:
            return header["role"]


class EnrollmentManager:
    """Coordinator side: collect announcements, select roles.

    Selection policy (reference behavior reconstructed from SURVEY.md §2
    "trainer/evaluator selection"): the LAST enrollee — in announcement
    order — becomes the evaluator when ``want_evaluator`` and at least two
    devices enrolled; everyone else trains.
    """

    def __init__(self, client: BrokerClient, mud_policy=None,
                 device_type: Optional[str] = None):
        """``mud_policy``: optional :class:`comm.mud.MudPolicy` — the
        CoLearn enrollment gate.  Devices whose MUD profile fails the
        policy (or is malformed) are REFUSED: recorded in ``rejected``
        with the reason, never listed in ``devices()``.

        ``device_type``: restrict this manager to ONE MUD device type —
        the per-type-federation topology (one coordinator per type over
        the same broker; devices of other types are simply not-mine,
        skipped without rejection).  Implies a profile is required."""
        self._client = client
        self._client.subscribe(ENROLL_TOPIC + "#")
        self._lock = threading.Lock()
        self._devices: dict[str, DeviceInfo] = {}
        self._profiles: dict[str, object] = {}    # device_id -> MudProfile
        self._order: list[str] = []
        self._mud_policy = mud_policy
        self._device_type = device_type
        self.rejected: dict[str, str] = {}        # device_id -> reason

    def _admit(self, info: DeviceInfo) -> None:
        from colearn_federated_learning_tpu.comm.mud import (
            MudError,
            MudProfile,
        )

        profile, parse_err = None, None
        if info.mud:
            try:
                profile = MudProfile.from_json(info.mud)
            except MudError as e:
                parse_err = e
        if self._mud_policy is not None:
            try:
                if parse_err is not None:
                    raise parse_err
                self._mud_policy.check(profile, info.device_id)
            except MudError as e:
                with self._lock:
                    self.rejected[info.device_id] = str(e)
                    # A previously admitted device that re-announces with
                    # a now-rejected profile is withdrawn FROM THE
                    # MANAGER: it no longer appears in devices()/
                    # profile_of, and the elastic admission path will not
                    # re-admit it.  A coordinator that already captured
                    # the device in its trainers list keeps its own copy
                    # — mid-run eviction is the coordinator's call (the
                    # straggler/eviction machinery), not the manager's.
                    self._withdraw_locked(info.device_id)
                return
        if self._device_type is not None and (
            profile is None or profile.device_type != self._device_type
        ):
            # Another type's device (or profile-less): not-mine, not a
            # rejection — a sibling per-type manager owns it.
            with self._lock:
                self._withdraw_locked(info.device_id)
            return
        with self._lock:
            self.rejected.pop(info.device_id, None)
            if info.device_id not in self._devices:
                self._order.append(info.device_id)
            self._devices[info.device_id] = info
            self._profiles[info.device_id] = profile

    def _withdraw_locked(self, device_id: str) -> None:
        """Remove every manager-side trace of ``device_id`` (call with
        ``self._lock`` held) — shared by the rejection and not-my-type
        paths so their bookkeeping can never drift."""
        if device_id in self._devices:
            del self._devices[device_id]
            self._order.remove(device_id)
            self._profiles.pop(device_id, None)

    def poll(self, duration: float) -> None:
        """Drain announcements for ``duration`` seconds."""
        deadline = time.monotonic() + duration
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            try:
                header, _ = self._client.recv(timeout=remaining)
            except (TimeoutError, OSError):
                return
            if (header.get("op") == "suback"
                    or not str(header.get("topic", "")).startswith(
                        ENROLL_TOPIC)):
                continue
            self._admit(_parse_enroll(header))

    def profile_of(self, device_id: str):
        """The admitted device's parsed MudProfile (None when it enrolled
        without one or no policy parses profiles)."""
        with self._lock:
            return self._profiles.get(device_id)

    def wait_for(self, n: int, timeout: float, poll_step: float = 0.2) -> None:
        """Poll until at least ``n`` devices enrolled (or raise)."""
        deadline = time.monotonic() + timeout
        while len(self.devices()) < n:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {len(self.devices())}/{n} devices enrolled"
                )
            self.poll(poll_step)

    def devices(self) -> list[DeviceInfo]:
        with self._lock:
            return [self._devices[d] for d in self._order]

    def assign_roles(self, want_evaluator: bool = True
                     ) -> tuple[list[DeviceInfo], Optional[DeviceInfo]]:
        """Pick (trainers, evaluator) and publish retained role messages."""
        devs = self.devices()
        if not devs:
            raise RuntimeError("no devices enrolled")
        evaluator = None
        trainers = devs
        if want_evaluator and len(devs) >= 2:
            evaluator = devs[-1]
            trainers = devs[:-1]
        for d in trainers:
            self._client.publish(ROLE_TOPIC + d.device_id,
                                 {"role": "trainer"}, retain=True)
        if evaluator is not None:
            self._client.publish(ROLE_TOPIC + evaluator.device_id,
                                 {"role": "evaluator"}, retain=True)
        return trainers, evaluator


def admit_late_joiners(enroll: "EnrollmentManager", broker, trainers: list,
                       evaluator, clients: dict, poll: float = 0.1) -> list:
    """Elastic membership, shared by BOTH coordinators (sync round loop and
    async pumps): poll enrollment, give every newcomer the trainer role
    (retained), open its tensor connection into ``clients`` and append it
    to ``trainers``.  Returns the admitted device ids."""
    from colearn_federated_learning_tpu.comm.transport import TensorClient

    enroll.poll(poll)
    known = {d.device_id for d in trainers}
    if evaluator is not None:
        known.add(evaluator.device_id)
    admitted = []
    for d in enroll.devices():
        if d.device_id in known:
            continue
        try:
            clients[d.device_id] = TensorClient(
                d.host, d.port, timeout=protocol.CONNECT_TIMEOUT,
                ident=d.device_id)
        except OSError:
            # Announced but unreachable (died between enroll and admit):
            # skip it this poll — survivable, counted, never silent.
            protocol.count_suppressed()
            continue
        broker.publish(ROLE_TOPIC + d.device_id,
                       {"role": "trainer"}, retain=True)
        trainers.append(d)
        admitted.append(d.device_id)
    return admitted
