"""MUD (RFC 8520) device profiles for enrollment gating.

CoLearn's defining idea (SURVEY.md §0, EdgeSys'20) is combining
Manufacturer Usage Description profiles with federated learning: an IoT
device presents its MUD profile, the network derives what the device IS
(manufacturer/model/type), and the FL layer uses that identity to decide
WHO may join a federation and WHICH federation (per-device-type anomaly
models).  The reference repo is the FL half of that system; this module
rebuilds the MUD-facing surface it plugs into:

- :class:`MudProfile`: the subset of an RFC 8520 MUD file the FL layer
  consumes (``mud-url``, ``mud-version``, ``is-supported``,
  ``systeminfo``, ``mfg-name``/``model-name`` from the extension fields,
  ``cache-validity``), parsed from the standard ``ietf-mud:mud``
  container with loud errors for malformed files.
- :class:`MudPolicy`: the coordinator-side gate — require a profile,
  allowlist device types, refuse unsupported devices.  Evaluated at
  enrollment (comm/enrollment.py), mirroring how the CoLearn system
  admits devices to an FL task by MUD identity.
- :func:`group_by_device_type`: partition enrolled devices per type —
  the input topology for per-type federations (fed/hierarchical.py
  groups, or one ClusteredLearner per type).

Profiles travel as JSON on the retained enrollment record (the broker
control plane), NOT fetched from the manufacturer URL — this sandbox has
no network, and in the reference deployment the MUD manager has already
retrieved/verified the file; the FL layer only consumes its contents.
Signature verification (RFC 8520 §13) is the MUD manager's job and out
of scope here, stated honestly.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional


class MudError(ValueError):
    """Malformed or policy-rejected MUD profile."""


@dataclasses.dataclass(frozen=True)
class MudProfile:
    mud_url: str
    mud_version: int = 1
    is_supported: bool = True
    systeminfo: str = ""
    mfg_name: str = ""
    model_name: str = ""
    device_type: str = ""          # CoLearn-level classification
    cache_validity_hours: int = 48

    @classmethod
    def from_json(cls, text: str) -> "MudProfile":
        """Parse the ``ietf-mud:mud`` container of an RFC 8520 file."""
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise MudError(f"MUD profile is not valid JSON: {e}") from None
        container = doc.get("ietf-mud:mud")
        if not isinstance(container, dict):
            raise MudError(
                "MUD profile lacks the 'ietf-mud:mud' container "
                "(RFC 8520 section 2)"
            )
        url = container.get("mud-url", "")
        if not isinstance(url, str) or not url.startswith("https://"):
            # RFC 8520 section 3.3: mud-url MUST use the https scheme.
            raise MudError(f"mud-url must be an https URL, got {url!r}")
        version = container.get("mud-version", 1)
        if version != 1:
            raise MudError(f"unsupported mud-version {version!r}")
        try:
            return cls(
                mud_url=url,
                mud_version=int(version),
                is_supported=bool(container.get("is-supported", True)),
                systeminfo=str(container.get("systeminfo", "")),
                mfg_name=str(container.get("mfg-name", "")),
                model_name=str(container.get("model-name", "")),
                device_type=str(container.get(
                    "colearn:device-type",
                    container.get("model-name", ""))),
                cache_validity_hours=int(container.get("cache-validity", 48)),
            )
        except (TypeError, ValueError) as e:
            # Wrong-typed leaf values (e.g. cache-validity: "48h") must
            # surface as MudError — anything else would escape the
            # enrollment loop's handler and crash the coordinator on one
            # malformed enrollee.
            raise MudError(f"malformed MUD field: {e}") from None

    def to_json(self) -> str:
        return json.dumps({"ietf-mud:mud": {
            "mud-version": self.mud_version,
            "mud-url": self.mud_url,
            "is-supported": self.is_supported,
            "systeminfo": self.systeminfo,
            "mfg-name": self.mfg_name,
            "model-name": self.model_name,
            "colearn:device-type": self.device_type,
            "cache-validity": self.cache_validity_hours,
        }})


@dataclasses.dataclass(frozen=True)
class MudPolicy:
    """Coordinator-side enrollment gate.

    - ``require_profile``: devices without a MUD profile are refused.
    - ``allowed_types``: non-empty → only these device types enroll.
    - ``require_supported``: refuse devices whose manufacturer no longer
      supports them (RFC 8520 ``is-supported`` false — exactly the
      stale-firmware population an anomaly-detection federation should
      not learn 'normal' from).
    """

    require_profile: bool = False
    allowed_types: tuple[str, ...] = ()
    require_supported: bool = True

    def check(self, profile: Optional[MudProfile],
              device_id: str = "?") -> None:
        """Raise :class:`MudError` when the device must be refused."""
        if profile is None:
            # A type allowlist implies the profile is required: otherwise
            # any device could bypass the gate by simply withholding its
            # profile.
            if self.require_profile or self.allowed_types:
                raise MudError(
                    f"device {device_id}: enrollment requires a MUD "
                    "profile and none was presented"
                )
            return
        if self.require_supported and not profile.is_supported:
            raise MudError(
                f"device {device_id}: manufacturer marked this device "
                "unsupported (is-supported=false)"
            )
        if self.allowed_types and profile.device_type not in self.allowed_types:
            raise MudError(
                f"device {device_id}: device type "
                f"{profile.device_type!r} is not in the allowed set "
                f"{sorted(self.allowed_types)}"
            )


def group_by_device_type(devices_with_profiles) -> dict[str, list]:
    """``{device_type: [DeviceInfo, ...]}`` over (info, profile) pairs —
    the per-type topology CoLearn trains one anomaly model per device
    class over.  Profile-less devices group under ``""``."""
    groups: dict[str, list] = {}
    for info, profile in devices_with_profiles:
        key = profile.device_type if profile is not None else ""
        groups.setdefault(key, []).append(info)
    return groups
