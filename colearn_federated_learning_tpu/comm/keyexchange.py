"""Diffie-Hellman key agreement for wire-plane secure aggregation.

Why this exists: the engine-plane masking (privacy/secure_agg.py) derives
pair keys from the shared experiment seed — fine for a SIMULATION, where
one process holds every client anyway, but on the socket deployment the
coordinator also holds that seed and could expand any pair's mask and
unmask any single client, which is precisely what Bonawitz-pattern secure
aggregation exists to prevent (1611.04482, pattern only; PAPERS.md).

Here every worker generates an ephemeral keypair, publishes the PUBLIC
half on its retained enrollment topic (comm/enrollment.py), and derives
each pairwise mask PRG seed from the DH shared secret — which only the
two pair members can compute.  The coordinator sees public keys and
masked updates only.

Construction: finite-field DH over the RFC 3526 group-14 2048-bit MODP
prime (stdlib-only: ``pow(g, x, p)`` + SHA-256), 512-bit exponents.  The
prime is a safe prime, so the only small-subgroup elements are {0, 1,
p-1}; :func:`validate_public` rejects each by name (plus the range
check) and counts rejections under ``comm.keyexchange_rejected_total``.  Pair key: SHA-256(secret ‖ context-tag ‖ sorted pair ids) →
64-bit PRNG seed; the round index is folded in on-device so one exchange
covers every round.

Remaining trust model (honest statement): this defeats a PASSIVE
(honest-but-curious) coordinator.  An ACTIVE attacker who controls the
broker could substitute its own public keys (classic DH MITM) — defeating
that needs authenticated enrollment (device certificates), out of scope
here and called out in the README.
"""

from __future__ import annotations

import hashlib
import secrets

import jax
import numpy as np

# RFC 3526 §3, group 14: 2048-bit MODP prime, generator 2.
GROUP14_P = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)
GROUP14_G = 2

_CONTEXT = b"colearn-pairmask-v1"


def generate_keypair() -> tuple[int, int]:
    """(private, public) for one worker session.  512-bit exponent —
    comfortably above group 14's ~110-bit security level."""
    priv = secrets.randbits(512) | (1 << 511)     # top bit set: full size
    return priv, pow(GROUP14_G, priv, GROUP14_P)


class InvalidPublicKeyError(ValueError):
    """A peer published a degenerate or out-of-range DH public value.
    Subclasses ValueError so existing ``except ValueError`` call sites
    keep working; carries the rejection ``reason`` label."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(f"invalid DH public key ({reason})")


def _reject(reason: str) -> "InvalidPublicKeyError":
    # Lazy import: keyexchange must stay importable without dragging the
    # telemetry plane in at module load (mirrors protocol.py's pattern).
    from colearn_federated_learning_tpu import telemetry

    telemetry.get_registry().counter(
        "comm.keyexchange_rejected_total", labels={"reason": reason}
    ).inc()
    return InvalidPublicKeyError(reason)


def validate_public(pub: int) -> int:
    """Reject degenerate public values with a dedicated error and a
    labeled rejection counter.  In a safe-prime group the small-subgroup
    elements are exactly {0, 1, p-1} (orders —, 1, 2): a peer publishing
    one would force the pair's shared secret into a guessable set, letting
    a curious relay unmask that pair's stream, so each is named rather
    than lumped into the range check."""
    pub = int(pub)
    if pub == 0:
        raise _reject("zero")
    if pub == 1:
        raise _reject("identity")
    if pub == GROUP14_P - 1:
        raise _reject("order_two")
    if not 1 < pub < GROUP14_P - 1:
        raise _reject("out_of_range")
    return pub


def shared_secret(priv: int, pub_other: int) -> bytes:
    """32-byte shared secret for one pair (hashing fixes the length and
    breaks the algebraic structure of the raw DH value)."""
    validate_public(pub_other)
    z = pow(pub_other, priv, GROUP14_P)
    return hashlib.sha256(z.to_bytes(256, "big")).digest()


def pair_prng_key(secret: bytes, id_a: int, id_b: int) -> jax.Array:
    """uint32[2] PRNG key-data for one pair's mask stream.  Symmetric in
    (id_a, id_b) — both members expand the identical stream, which is
    what makes the masks cancel inside the aggregate sum.  The round
    index is NOT baked in; callers fold it on-device
    (privacy/secure_agg.pairwise_mask_with_keys)."""
    lo, hi = sorted((int(id_a), int(id_b)))
    digest = hashlib.sha256(
        _CONTEXT + secret + lo.to_bytes(8, "big") + hi.to_bytes(8, "big")
    ).digest()
    words = np.frombuffer(digest[:8], dtype=">u4").astype(np.uint32)
    return jax.numpy.asarray(words)


def encode_public(pub: int) -> str:
    return format(pub, "x")


def decode_public(text: str) -> int:
    return validate_public(int(text, 16))
