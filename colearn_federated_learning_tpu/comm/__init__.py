"""Cross-process federation: control plane + tensor plane.

The reference federates REAL devices: a paho-mqtt broker carries device
enrollment / role negotiation, and PySyft websocket workers carry tensors
(SURVEY.md §1 "Enrollment / discovery" and "Communication").  The rebuild
keeps that two-plane architecture with zero external dependencies:

- ``protocol``:   length-prefixed JSON-header + binary-body framing.
- ``broker``:     tiny TCP pub/sub broker (the MQTT equivalent).
- ``enrollment``: device announce → coordinator selects trainer/evaluator
  roles (the reference's MQTT topic negotiation).
- ``transport``:  per-device tensor server/client moving model pytrees
  (the PySyft websocket-worker equivalent).
- ``worker``:     device process — local shard + jit local trainer.
- ``coordinator``: round loop over enrolled devices with per-round
  timeouts (straggler drop), server strategies, evaluator scoring.
- ``mud``:        RFC 8520 device profiles + the enrollment gate
  (CoLearn's MUD-identity pattern).
- ``keyexchange``: DH pair keys for wire-plane secure aggregation.

On-device simulation (fed/engine.py) is the fast path; this package is the
cross-silo path where participants are separate processes/hosts.  Both use
the same config, trainer construction (fed/setup.py) and wire payloads
(utils/serialization.py npz), so a silo can move between modes freely.
"""

from colearn_federated_learning_tpu.comm.broker import MessageBroker  # noqa: F401
from colearn_federated_learning_tpu.comm.coordinator import (  # noqa: F401
    FederatedCoordinator,
)
from colearn_federated_learning_tpu.comm.mud import (  # noqa: F401
    MudPolicy,
    MudProfile,
)
from colearn_federated_learning_tpu.comm.worker import DeviceWorker  # noqa: F401
