"""Per-device-type federations — the CoLearn deployment topology.

The CoLearn system's point (SURVEY.md §0) is that MUD identity decides
WHICH federation a device joins: cameras train the camera anomaly model,
bulbs the bulb model — one global model across heterogeneous device
classes would smear their distinct "normal" traffic together.  This
module runs that topology over the in-tree planes:

1. discover device types from the retained enrollment records (every
   worker announces its RFC 8520 profile, comm/mud.py);
2. one :class:`~.coordinator.FederatedCoordinator` per type, each
   filtering enrollment to ITS type (sibling devices are not-mine, not
   rejections), each training its OWN global model;
3. federations run in THREADS over the shared broker — a slow device
   class does not stall the others (each coordinator already owns its
   round deadline).

``colearn coordinate --per-type`` is the CLI entry.
"""

from __future__ import annotations

import threading
from typing import Optional

from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.comm.broker import BrokerClient
from colearn_federated_learning_tpu.comm.coordinator import (
    FederatedCoordinator,
)
from colearn_federated_learning_tpu.comm.enrollment import EnrollmentManager
from colearn_federated_learning_tpu.comm.mud import group_by_device_type
from colearn_federated_learning_tpu.utils.config import ExperimentConfig


def discover_types(broker_host: str, broker_port: int,
                   min_devices: int, timeout: float,
                   mud_policy=None) -> dict[str, list]:
    """``{device_type: [DeviceInfo, ...]}`` from the retained enrollment
    records, waiting until at least ``min_devices`` admitted devices are
    visible.  Profile-less devices group under ``""`` (callers decide
    whether an untyped federation makes sense)."""
    client = BrokerClient(broker_host, broker_port,
                          timeout=protocol.CONNECT_TIMEOUT)
    try:
        enroll = EnrollmentManager(client, mud_policy=mud_policy)
        enroll.wait_for(min_devices, timeout)
        pairs = [(d, enroll.profile_of(d.device_id))
                 for d in enroll.devices()]
        return group_by_device_type(pairs)
    finally:
        client.close()


class PerTypeFederation:
    """One federation per discovered MUD device type (see module doc)."""

    def __init__(
        self,
        config: ExperimentConfig,
        broker_host: str,
        broker_port: int,
        round_timeout: float = 60.0,
        mud_policy=None,
        min_devices_per_type: int = 2,
    ):
        self.config = config
        self.broker = (broker_host, broker_port)
        self.round_timeout = round_timeout
        self.mud_policy = mud_policy
        self.min_per_type = min_devices_per_type
        self.coordinators: dict[str, FederatedCoordinator] = {}
        self.skipped: dict[str, int] = {}     # type -> too-few device count
        self.histories: dict[str, list] = {}
        self.errors: dict[str, str] = {}

    def run(self, min_devices: int, enroll_timeout: float = 60.0,
            rounds: Optional[int] = None, want_evaluator: bool = False,
            log_fn=None) -> dict[str, list]:
        """Discover types, then train every type's federation to
        completion (threads; shared broker).  Returns per-type round
        histories; types with fewer than ``min_devices_per_type``
        devices are skipped and recorded in ``skipped``."""
        import dataclasses

        groups = discover_types(*self.broker, min_devices=min_devices,
                                timeout=enroll_timeout,
                                mud_policy=self.mud_policy)
        group_sizes: dict[str, int] = {}
        for dtype, devs in sorted(groups.items()):
            if not dtype or len(devs) < self.min_per_type:
                self.skipped[dtype] = len(devs)
                continue
            group_sizes[dtype] = len(devs)
            cfg = self.config.replace(run=dataclasses.replace(
                self.config.run,
                name=f"{self.config.run.name}_{dtype}",
            ))
            self.coordinators[dtype] = FederatedCoordinator(
                cfg, *self.broker, round_timeout=self.round_timeout,
                want_evaluator=want_evaluator, mud_policy=self.mud_policy,
                device_type=dtype,
            )

        def train(dtype: str, coord: FederatedCoordinator) -> None:
            try:
                # Wait for the FULL discovered cohort of this type, not
                # just the minimum: a replay that is still in flight must
                # not strand the tail devices role-less while their data
                # silently never contributes.
                coord.enroll(min_devices=group_sizes[dtype],
                             timeout=enroll_timeout)
                self.histories[dtype] = coord.fit(
                    rounds=rounds,
                    log_fn=(lambda rec, t=dtype: log_fn(t, rec))
                    if log_fn else None,
                )
            except Exception as e:  # noqa: BLE001 — per-type isolation:
                # one failing device class must not kill the others.
                self.errors[dtype] = f"{type(e).__name__}: {e}"

        threads = [
            threading.Thread(target=train, args=(t, c), daemon=True,
                             name=f"federate-{t}")
            for t, c in self.coordinators.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self.histories

    def close(self) -> None:
        for coord in self.coordinators.values():
            coord.close()
