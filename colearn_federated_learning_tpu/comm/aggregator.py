"""Aggregator tier: distributed ingest between devices and the root.

One coordinator folding every uplink byte caps the federation at a
single host's ingest bandwidth and fold CPU (ROADMAP "Distributed
aggregator tier"; the DisAgg / NET-SA composition result in PAPERS.md).
This module is the middle tier that removes the cap: N real
:class:`AggregatorServer` processes each own a contiguous slice of the
round cohort, run the SAME sparse-native :class:`StreamingFolder` the
root runs (comm/aggregation.py) over their slice, and emit ONE partial
sum upstream — so the root folds N partials instead of C cohort
updates, and per-process ingest bytes / fold CPU scale ~1/N
(``bench_fleet.py --ingest-sweep`` prices it).

Exactness: the root's cross-partial combine is float addition REGROUPED
at the slice boundaries, which is exactly what
``StreamingFolder(slices=...)`` computes flat — the parity tests pin
the tree fold BITWISE against that slice-blocked flat fold (dense and
topk uplinks, full and partial cohorts, replicated and tp-sharded
root).  With one aggregator the tree fold is bitwise identical to the
historical flat fold outright.

Robustness (the headline, not a footnote):

- every aggregator heartbeats a RETAINED broker record
  (``colearn/agg/<id>``, fresh ``ts`` each beat); the root checks
  heartbeat age before dispatch (bounded-deadline detection,
  ``run.agg_heartbeat_timeout``) and counts expiries;
- a fold request that fails — dead heartbeat, SIGKILLed process,
  connection reset mid-fold — RE-HOMES its whole slice to a surviving
  sibling aggregator inside the same round budget
  (``comm.agg_failovers_total{action="rehome"}``); only when no sibling
  survives does the slice quorum-drop with renormalization
  (``action="drop"`` — the mean divides by the folded weight, so the
  round stays well-defined).  ``faults/procsoak.run_agg_soak`` chaos-
  gates this with a real mid-round SIGKILL against a flat oracle.

Secure-agg composition: pairwise masks cancel within any COMPLETE sum,
so the root passes each device its SLICE as the pairing cohort — every
mask pair lives inside one aggregator's partial, each partial stays
unopenable (self-masks come off only at the root's per-slice recovery),
and a fully-dropped slice orphans no mask halves at all.

The aggregator is model-agnostic: it decodes the relayed broadcast
frame into the global-params tree (that IS its shapes template),
re-encodes it once, and fans the shared frame out to its slice —
serialize-once preserved per tier.  ``compress_down`` must be ``none``
in tree mode (the resync protocol is not relayed; the coordinator
validates eagerly).
"""

from __future__ import annotations

import concurrent.futures as cf
import math
import threading
import time
from typing import Any, Optional, Sequence

from colearn_federated_learning_tpu.comm.broker import BrokerClient
from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.comm.transport import (
    TensorClient,
    TensorServer,
)
from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.faults import lockwitness
from colearn_federated_learning_tpu.utils.config import ExperimentConfig

# Retained announce/heartbeat topic per aggregator (control plane).
AGG_TOPIC = "colearn/agg/"


def slice_cohort(cohort: Sequence[Any], n: int) -> list[list[Any]]:
    """Partition ``cohort`` (already in cohort order) into ``n``
    contiguous slices whose sizes differ by at most one — the tree's
    slice layout AND the flat parity oracle's block layout, so both
    sides regroup the fold sum identically.  Slices may be empty when
    ``n`` exceeds the cohort."""
    n = max(1, int(n))
    base, rem = divmod(len(cohort), n)
    out, start = [], 0
    for i in range(n):
        size = base + (1 if i < rem else 0)
        out.append(list(cohort[start:start + size]))
        start += size
    return out


def _device_key(d: Any) -> str:
    """Canonical string id for a cohort entry — device tuples
    ``(id, host, port)`` on the sync plane, bare ids on the async one."""
    if isinstance(d, (tuple, list)):
        return str(int(d[0]))
    return str(d)


def assign_slices(cohort: Sequence[Any], n: int,
                  scores: Optional[dict] = None) -> list[list[Any]]:
    """Health-driven slice assignment: partition ``cohort`` into ``n``
    slices of the same sizes as :func:`slice_cohort`, but ordered by the
    health ledger's straggler scores (ascending) so chronic stragglers
    concentrate in the LAST — deepest-buffer — slices instead of
    poisoning every slice's fold cadence.

    ``scores`` maps canonical device ids (str) to straggler scores;
    ``None`` or an all-equal map degrades to the contiguous divmod
    EXACTLY (the sort below is stable over the original order), so the
    default data path — no health ledger — is byte-identical to before.
    """
    if scores is None:
        return slice_cohort(cohort, n)
    vals = [float(scores.get(_device_key(d), 0.0)) for d in cohort]
    if len(set(vals)) <= 1:
        return slice_cohort(cohort, n)
    order = sorted(range(len(cohort)), key=lambda i: (vals[i], i))
    return slice_cohort([cohort[i] for i in order], n)


class AggregatorServer:
    """One aggregator process: a tensor server folding its device slice.

    Serves ``{"op": "fold"}`` requests from the root: the request body
    is the round's broadcast frame (decoded to the params tree by the
    transport), the header carries the slice's device addresses, the
    (slice-local) secure-agg cohort and relayed share inboxes.  The
    reply is the slice's weighted-sum tree plus fold bookkeeping
    (``total_w``, ``loss_sum``, ``folded_ids``, ``failed``, ``stale``).
    """

    def __init__(self, config: ExperimentConfig, agg_id: int,
                 broker_host: Optional[str] = None,
                 broker_port: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_s: float = 0.5):
        self.config = config
        self.agg_id = int(agg_id)
        # Spans are captured per request and shipped UPSTREAM in the
        # reply meta (the root owns the stitched trace); the local buffer
        # additionally feeds the flight recorder's span tail, so a
        # SIGKILLed aggregator's last folds survive in its flight dump.
        self.tracer = telemetry.Tracer(
            process=f"aggregator-{self.agg_id}", max_spans=4096)
        # Per-device health feed (telemetry/health.py), gated on the run
        # config so the default data path writes nothing.
        self.health = None
        if config.run.health_dir:
            self.health = telemetry.HealthLedger(
                config.run.health_dir, f"aggregator{self.agg_id}")
        self._server = TensorServer(self._handle, host=host, port=port,
                                    ident=f"agg:{self.agg_id}")
        self._broker_addr = (broker_host, broker_port)
        self._broker: Optional[BrokerClient] = None
        self.heartbeat_s = float(heartbeat_s)
        self._stop = threading.Event()
        self._hb: Optional[threading.Thread] = None
        # Retry policy mirrors the root's (config.run.comm_retries) so a
        # flaky device gets the same second chance either way.
        from colearn_federated_learning_tpu.comm.transport import RetryPolicy

        self.retry = (
            RetryPolicy(max_retries=config.run.comm_retries,
                        backoff_base=config.run.comm_backoff_base,
                        backoff_max=config.run.comm_backoff_max)
            if config.run.comm_retries > 0 else None
        )
        # Buffered-async state (tree-async mode): a per-slice buffer the
        # root fills contribution-by-contribution ("abuf") and drains as
        # partial folds ("adrain").  The slice's own arrival estimator
        # sizes the fold threshold K (auto-K, slew-limited).
        from colearn_federated_learning_tpu.telemetry.arrival import (
            ArrivalEstimator,
        )

        self.arrival = ArrivalEstimator()
        # --fold-device: slice folds run through the fused device kernel
        # (ops/fold_kernel.py); the host fold stays the parity oracle.
        self._fold_device = bool(getattr(config.run, "fold_device", False))
        self._abuf_cv = lockwitness.condition(f"agg{agg_id}.abuf_cv")
        self._abuf_folder = None            # StreamingFolder | None
        self._abuf_shapes = None
        self._abuf_entries: dict[str, dict] = {}   # dedup key -> bookkeeping
        self._abuf_k: Optional[int] = None         # slew anchor
        self._abuf_dedup = 0

    # ------------------------------------------------------------------
    @property
    def host(self) -> str:
        return self._server.host

    @property
    def port(self) -> int:
        return self._server.port

    def start(self) -> "AggregatorServer":
        self._server.start()
        bh, bp = self._broker_addr
        if bh is not None:
            self._broker = BrokerClient(bh, bp,
                                        timeout=protocol.CONNECT_TIMEOUT)
            self._announce()
            self._hb = threading.Thread(
                target=self._heartbeat_loop,
                name=f"agg-{self.agg_id}-heartbeat", daemon=True)
            self._hb.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._hb is not None:
            self._hb.join(timeout=2.0)
        self._server.stop()
        if self._broker is not None:
            self._broker.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _announce(self) -> None:
        self._broker.publish(AGG_TOPIC + str(self.agg_id), {
            "agg_id": self.agg_id, "host": self.host, "port": self.port,
            "ts": time.time(),
        }, retain=True)

    def _heartbeat_loop(self) -> None:
        """Republish the retained announce with a fresh ``ts`` every
        beat — the root's liveness signal.  A dead broker is reconnected
        with the same heal-in-place pattern as the worker watchdog (the
        retained record died with the old broker)."""
        bh, bp = self._broker_addr
        while not self._stop.wait(self.heartbeat_s):
            try:
                if self._broker is None or not self._broker.alive():
                    fresh = BrokerClient(bh, bp,
                                         timeout=protocol.CONNECT_TIMEOUT)
                    if self._broker is not None:
                        self._broker.close()
                    self._broker = fresh
                self._announce()
            except OSError:
                protocol.count_suppressed()   # broker down: retry next beat
                continue

    # ------------------------------------------------------------------
    def _handle(self, header: dict, tree: Any) -> tuple[dict, Any]:
        op = header.get("op")
        if op == "fold":
            return self._fold(header, tree)
        if op == "aprep":
            return self._aprep(header, tree)
        if op == "abuf":
            return self._abuf(header, tree)
        if op == "adrain":
            return self._adrain(header)
        if op == "info":
            return ({"meta": {"agg_id": self.agg_id,
                              "host": self.host, "port": self.port}}, None)
        return ({"status": "error", "error": f"unknown op {op!r}"}, None)

    # ------------------------------------------------- buffered (async) --
    def _aprep(self, header: dict, tree: Any) -> tuple[dict, Any]:
        """Install the fold-shapes template and (re)open an empty buffer.

        The async root sends this once per aggregator connection — at
        enrollment and again after an aggregator restart (a restarted
        process announces on a fresh port with no buffered state, which
        is what makes re-homing double-fold-free: contributions only
        ever live in ONE process's buffer)."""
        from colearn_federated_learning_tpu.comm.aggregation import (
            StreamingFolder,
        )

        if tree is None:
            return ({"status": "error",
                     "error": "aprep carried no shapes template"}, None)
        meta_in = header.get("meta") or {}
        shapes = tree["factors"] if meta_in.get("lora") else tree
        with self._abuf_cv:
            self._abuf_shapes = shapes
            self._abuf_folder = StreamingFolder(
                shapes, device_fold=self._fold_device)
            self._abuf_entries = {}
            self._abuf_dedup = 0
            self._abuf_cv.notify_all()
        return ({"meta": {"agg_id": self.agg_id, "prepared": True}}, None)

    def _abuf(self, header: dict, tree: Any) -> tuple[dict, Any]:
        """Stage ONE device contribution into the open buffer.

        ``header["key"]`` is the per-contribution dedup key
        (``{version:08d}@{device}``): staging is idempotent under it — a
        duplicate (re-homed copy racing the original, or a root retry)
        REPLACES the staged copy instead of folding twice.  The fold
        itself happens at arrival (StreamingFolder.add: decompress +
        scale, the dominant host cost), so drain time is just the cheap
        deterministic summation."""
        if tree is None:
            return ({"status": "error",
                     "error": "abuf carried no delta"}, None)
        key = str(header.get("key"))
        dev = str(header.get("device"))
        meta = dict(header.get("meta") or {})
        meta["client_id"] = key
        reg = telemetry.get_registry()
        with self._abuf_cv:
            if self._abuf_folder is None:
                return ({"status": "error",
                         "error": "aggregator buffer not prepared "
                                  "(aprep first)"}, None)
            dup = self._abuf_folder.discard(key)
            if dup:
                self._abuf_dedup += 1
                reg.counter("comm.agg_buffer_dedup_total",
                            labels={"agg": str(self.agg_id)}).inc()
            self._abuf_folder.add(meta, tree)
            self._abuf_entries[key] = {
                "device": dev,
                "version": int(header.get("version", 0)),
                "weight": float(meta.get("weight", 1.0)),
                "rehomed": bool(header.get("rehomed")),
            }
            self.arrival.observe(dev, now=time.monotonic())
            staged = len(self._abuf_entries)
            self._abuf_cv.notify_all()
        reg.counter("comm.agg_buffer_staged_total",
                    labels={"agg": str(self.agg_id)}).inc()
        reg.gauge("comm.agg_buffer_occupancy",
                  labels={"agg": str(self.agg_id)}).set(staged)
        return ({"meta": {"agg_id": self.agg_id, "staged": staged,
                          "dedup": dup}}, None)

    def _auto_k(self, interval_s: float, slice_devices: int) -> int:  # colearn: holds(_abuf_cv)
        """Auto-K for this slice: the K that folds once per
        ``interval_s`` at the slice's observed arrival rate, clamped to
        the slice size and slew-limited to [K/2, 3K/2] per drain (the
        PR 14 controller idiom) so one burst cannot whiplash the
        threshold.  Caller holds ``_abuf_cv``."""
        hi = max(1, int(slice_devices)) if slice_devices else 1 << 10
        cur = self._abuf_k if self._abuf_k is not None else min(4, hi)
        k = self.arrival.recommend_buffer(interval_s, lo=1, hi=hi,
                                          current=cur)
        k = max(max(1, cur // 2), min(k, max(2, cur * 3 // 2)))
        k = max(1, min(k, hi))
        self._abuf_k = k
        return k

    def _adrain(self, header: dict) -> tuple[dict, Any]:
        """Long-poll drain: block until the buffer reaches its auto-K (or
        the poll budget expires), then finalize and ship ONE partial fold
        upstream with the dispatch-version metadata the root needs to
        resolve staleness against the partial's OLDEST constituent
        version.  An empty expiry replies ``count: 0`` (idle poll)."""
        interval = float(header.get("interval_s", 2.0))
        budget = float(header.get("timeout", max(2.0 * interval, 1.0)))
        slice_n = int(header.get("slice_devices", 0))
        deadline = time.monotonic() + budget
        reg = telemetry.get_registry()
        with self._abuf_cv:
            if self._abuf_folder is None:
                return ({"status": "error",
                         "error": "aggregator buffer not prepared "
                                  "(aprep first)"}, None)
            while True:
                k = self._auto_k(interval, slice_n)
                if len(self._abuf_entries) >= k:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0.0:
                    break
                self._abuf_cv.wait(timeout=min(remaining, 0.05))
                if self._abuf_folder is None:
                    return ({"status": "error",
                             "error": "buffer reset mid-drain"}, None)
            rate = self.arrival.rate()
            reg.gauge("comm.agg_buffer_k",
                      labels={"agg": str(self.agg_id)}).set(k)
            reg.gauge("comm.agg_arrival_rate_per_s",
                      labels={"agg": str(self.agg_id)}).set(rate)
            if not self._abuf_entries:
                return ({"meta": {"agg_id": self.agg_id, "count": 0,
                                  "buffer_k": k,
                                  "arrival_rate_per_s": rate}}, None)
            folder = self._abuf_folder
            entries = self._abuf_entries
            dedup = self._abuf_dedup
            # Re-open the window: arrivals racing this drain stage into
            # the NEXT partial (never lost, never double-folded).
            from colearn_federated_learning_tpu.comm.aggregation import (
                StreamingFolder,
            )

            self._abuf_folder = StreamingFolder(
                self._abuf_shapes, device_fold=self._fold_device)
            self._abuf_entries = {}
            self._abuf_dedup = 0
        folder.finalize()
        keys = folder.folded_ids     # sorted: version-then-device order
        devices = [entries[c]["device"] for c in keys]
        versions = [entries[c]["version"] for c in keys]
        weights = [entries[c]["weight"] for c in keys]
        rehomed = sorted({entries[c]["device"] for c in keys
                          if entries[c]["rehomed"]})
        reg.counter("comm.agg_partials_shipped_total",
                    labels={"agg": str(self.agg_id)}).inc()
        reg.counter("comm.agg_folds_total",
                    labels={"agg": str(self.agg_id)}).inc()
        reg.gauge("comm.agg_buffer_occupancy",
                  labels={"agg": str(self.agg_id)}).set(0)
        out_meta = {
            "agg_id": self.agg_id,
            "count": len(keys),
            "keys": keys,
            "devices": devices,
            "versions": versions,
            "weights": weights,
            "rehomed": rehomed,
            "oldest_version": min(versions),
            "total_w": folder.total_w,
            "loss_sum": folder.loss_sum,
            "buffer_k": k,
            "dedup": dedup,
            "fold_s": folder.fold_s,
            "densify_avoided": folder.densify_avoided,
            "arrival_rate_per_s": rate,
        }
        return ({"meta": out_meta}, folder.wsum)

    def _fold(self, header: dict, tree: Any) -> tuple[dict, Any]:
        """Relay the broadcast to this slice's devices, fold the replies
        sparse-natively, reply with ONE partial sum.

        Trace stitching: the whole slice-fold runs under an
        ``aggregator.fold`` span parented on the root's round span (the
        fold request carries the root's context); each relayed train
        request carries THIS span's context, so worker spans parent onto
        the tier that actually dispatched them.  The reply ships the
        harvested worker spans plus this tier's own captured spans
        upstream, completing the coordinator → aggregator → worker chain
        in one trace."""
        from colearn_federated_learning_tpu.comm.aggregation import (
            StreamingFolder,
        )
        from colearn_federated_learning_tpu.utils.serialization import (
            pytree_to_bytes,
        )

        if tree is None:
            return ({"status": "error",
                     "error": "fold request carried no params frame"}, None)
        r = int(header.get("round", 0))
        devices = header.get("devices") or []
        cohort = header.get("cohort")
        shares_in = header.get("shares_in") or {}
        budget = float(header.get("timeout", 30.0))
        meta_in = header.get("meta") or {}
        ctx = protocol.extract_trace(header)
        # Serialize-once per tier: ONE re-encode of the decoded broadcast,
        # shared read-only by every slice send below.
        body = memoryview(pytree_to_bytes(tree, meta_in or None))
        # The decoded params tree IS the shapes template (StreamingFolder
        # only reads leaf shapes), so the aggregator needs no model code.
        # Under lora the broadcast is a {"base", "factors"} composite
        # (meta carries the ``lora`` marker) and the replies are FACTOR
        # trees — the factors half is the fold template.
        order = [str(int(d[0])) for d in devices]
        shapes = tree["factors"] if meta_in.get("lora") else tree
        folder = StreamingFolder(shapes, order=order,
                                 device_fold=self._fold_device)
        stale: list[str] = []
        failed: list[str] = []
        worker_spans: list = []
        deadline = time.monotonic() + budget

        with self.tracer.capture() as captured:
            with self.tracer.span("aggregator.fold", parent=ctx,
                                  agg=self.agg_id, round=r) as fold_sp:
                # Pool threads below have empty span stacks; hand them the
                # fold span's identity explicitly (the coordinator's
                # fan-out does the same with its round context).
                fold_ctx = fold_sp.context

                def ask(dev):
                    did, dhost, dport = (str(int(dev[0])), str(dev[1]),
                                         int(dev[2]))
                    req = {"op": "train", "round": r}
                    protocol.attach_trace(req, fold_ctx)
                    if cohort is not None:
                        req["cohort"] = cohort
                    inbox = shares_in.get(did)
                    if inbox:
                        req["shares_in"] = inbox
                    cli = TensorClient(dhost, dport,
                                       timeout=protocol.CONNECT_TIMEOUT,
                                       ident=did)
                    try:
                        hdr, delta = cli.request(req, body=body,
                                                 timeout=budget,
                                                 retry=self.retry,
                                                 deadline=deadline)
                        if hdr.get("status") != "ok":
                            raise RuntimeError(f"{did}: {hdr.get('error')}")
                        return hdr["meta"], delta
                    finally:
                        cli.close()

                if devices:
                    with cf.ThreadPoolExecutor(
                            max_workers=len(devices),
                            thread_name_prefix=f"agg{self.agg_id}-fanout",
                    ) as pool:
                        futs = {pool.submit(ask, d): str(int(d[0]))
                                for d in devices}
                        pending = dict(futs)

                        def take(fut, did):
                            try:
                                meta, delta = fut.result()
                            except Exception:
                                failed.append(did)
                                return
                            # Harvest the worker's spans (runs on the
                            # handler thread — no locking needed).  The
                            # worker.train span doubles as the device's
                            # observed round latency for the health feed.
                            spans = meta.pop(protocol.TRACE_SPANS_KEY,
                                             None) or []
                            worker_spans.extend(spans)
                            if self.health is not None:
                                for sd in spans:
                                    if str(sd.get("name")) == "worker.train":
                                        self.health.record(
                                            did, round=r,
                                            agg=str(self.agg_id),
                                            latency_s=float(
                                                sd.get("duration_s", 0.0)))
                            if int(meta.get("round", r)) != r:
                                stale.append(str(meta.get("client_id",
                                                          did)))
                                return
                            folder.add(meta, delta)

                        try:
                            for fut in cf.as_completed(futs,
                                                       timeout=budget):
                                take(fut, pending.pop(fut))
                        except cf.TimeoutError:     # colearn: noqa(CL003): stragglers charged to health ledger below
                            pass    # stragglers: charged below
                        for fut, did in pending.items():
                            if fut.done():
                                # Completed in the race window after
                                # as_completed gave up — the reply is
                                # here, use it (same leniency as the
                                # root's fan-out).
                                take(fut, did)
                            else:
                                fut.cancel()
                                failed.append(did)
                folder.finalize()
        reg = telemetry.get_registry()
        reg.counter("comm.agg_folds_total",
                    labels={"agg": str(self.agg_id)}).inc()
        reg.histogram("comm.agg_fold_time_s",
                      labels={"agg": str(self.agg_id)}).observe(
                          fold_sp.duration_s)
        failed_ids = sorted(set(failed), key=order.index)
        if self.health is not None:
            for did in failed_ids:
                self.health.record(did, round=r, agg=str(self.agg_id),
                                   deadline_miss=1)
            self.health.flush()
        out_meta = {
            "agg_id": self.agg_id,
            "round": r,
            "total_w": folder.total_w,
            "loss_sum": folder.loss_sum,
            "folded_ids": folder.folded_ids,
            "failed": failed_ids,
            "stale": stale,
            "fold_s": folder.fold_s,
            # Whole-tier wall time (span clock), distinct from fold_s
            # (CPU spent inside StreamingFolder.add/finalize): the root
            # records both as per-tier phase timings.
            "fold_wall_s": fold_sp.duration_s,
            "densify_avoided": folder.densify_avoided,
        }
        if ctx is not None:
            # Ship the whole tier's trace upstream: the workers' spans
            # plus our own (the fold span and anything under it).
            out_meta[protocol.TRACE_SPANS_KEY] = (
                worker_spans + [s.to_dict() for s in captured])
        if folder.wsum is None:
            return ({"meta": out_meta}, None)
        return ({"meta": out_meta}, folder.wsum)


def combine_partial_weights(total_ws: Sequence[float]) -> float:
    """Root-side sequential sum of partial weights — split out so the
    bench and tests share the exact arithmetic the coordinator runs."""
    total = 0.0
    for t in total_ws:
        total += float(t)
    return total


def run_aggregator_forever(config: ExperimentConfig, agg_id: int,
                           broker_host: str, broker_port: int,
                           heartbeat_s: float = 0.5) -> None:
    """CLI entry: announce, heartbeat, serve folds until killed."""
    agg = AggregatorServer(config, agg_id, broker_host, broker_port,
                           heartbeat_s=heartbeat_s)
    recorder = telemetry.get_flight_recorder()
    if recorder is not None:
        # Postmortem coverage for the middle tier: the recorder's
        # heartbeat dumps this tracer's span tail alongside the event
        # ring, so a SIGKILLed aggregator's last folds are attributable.
        recorder.attach_tracer(agg.tracer)
    agg.start()
    try:
        threading.Event().wait()
    finally:
        agg.stop()


def fetch_aggregators(sub: BrokerClient, known: dict,
                      drain_timeout: float = 0.05) -> dict:
    """Drain the retained ``colearn/agg/#`` subscription into ``known``
    (``agg_id -> {"host", "port", "ts"}``, latest record wins).  The
    root calls this at enrollment and before every tree dispatch — the
    heartbeat ``ts`` it refreshes is the bounded-deadline liveness
    signal."""
    while True:
        try:
            header, _ = sub.recv(timeout=drain_timeout)
        except TimeoutError:
            return known
        if not str(header.get("topic", "")).startswith(AGG_TOPIC):
            continue
        try:
            agg_id = int(header["agg_id"])
            known[agg_id] = {"host": str(header["host"]),
                             "port": int(header["port"]),
                             "ts": float(header.get("ts", 0.0))}
        except (KeyError, TypeError, ValueError):
            protocol.count_suppressed()   # malformed announce: never crash
            continue


def expected_ingest(cohort: int, n_aggregators: int, update_bytes: int,
                    partial_bytes: int) -> dict:
    """Analytic per-round ingest bill of the tree (shape-only pricing,
    same convention as the wire bench): each aggregator ingests
    ``ceil(C/N)`` device update frames; the root ingests ``N`` partial
    frames instead of ``C`` update frames."""
    per_agg_devices = math.ceil(cohort / max(1, n_aggregators))
    return {
        "agg_ingest_bytes": per_agg_devices * update_bytes,
        "root_ingest_bytes": n_aggregators * partial_bytes,
        "flat_root_ingest_bytes": cohort * update_bytes,
    }
